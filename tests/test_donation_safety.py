"""Buffer donation: the train step aliases params+opt state in place.

Donated operand buffers are DELETED by XLA the moment the step dispatches —
holding a stale reference to a pre-step param tree and using it afterwards
must raise jax's deleted-buffer error, while every engine-owned path
(run_steps, warm_scan, sync_to_model, state_dict, a second step) must never
trip it. The memory win is asserted chip-free from the compiled program:
without donation the step's peak carries a second copy of the training
state (alias bytes = 0), with donation it does not.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed.engine import TrainStepEngine


def _make(donate=True, seed=0):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
                               paddle.nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=net.parameters())
    return TrainStepEngine(net, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                           donate=donate)


def _batch(n=32):
    rng = np.random.RandomState(0)
    return (paddle.to_tensor(rng.randn(n, 16).astype(np.float32)),
            paddle.to_tensor(rng.randint(0, 4, (n,)).astype(np.int64)))


def test_reusing_donated_param_tree_raises_deleted_buffer():
    e = _make()
    x, y = _batch()
    stale = dict(e.params)           # user holds pre-step references
    e.step(x, y)
    name = next(iter(stale))
    assert stale[name].is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(stale[name])
    # the engine's own tree is the fresh post-update state and stays usable
    assert np.isfinite(np.asarray(e.params[name])).all()


def test_engine_paths_never_touch_donated_buffers():
    """run_steps / step / warm_scan / sync_to_model / state_dict in every
    order: no engine-owned path may observe a donated (deleted) buffer."""
    e = _make()
    x, y = _batch()
    e.run_steps(x, y, steps=3)
    e.step(x, y)
    e.warm_scan(x, y, steps=2)       # executes on copies, restores state
    losses = e.run_steps(x, y, steps=2)
    assert np.isfinite(np.asarray(losses._data)).all()
    sd = e.state_dict()
    for t in sd.values():
        assert np.isfinite(t.numpy()).all()
    e.sync_to_model()
    for p in e.model.parameters():
        assert np.isfinite(p.numpy()).all()


def test_donate_false_keeps_stale_trees_alive():
    e = _make(donate=False)
    x, y = _batch()
    stale = dict(e.params)
    e.step(x, y)
    name = next(iter(stale))
    assert not stale[name].is_deleted()
    np.asarray(stale[name])          # still readable


def test_donation_drops_compiled_step_peak_by_state_bytes():
    """The HLO-level high-water proof (chip-free twin of the StepTelemetry
    device-memory assertion): the undonated step holds TWO copies of
    params+opt state at peak, the donated step one. Model sized so the
    state dwarfs XLA's run-to-run temp-scheduling wobble."""
    x, y = _batch()
    arrays = [x._data, y._data]

    def make_big(donate):
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(16, 256),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(256, 4))
        opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                     parameters=net.parameters())
        return TrainStepEngine(net, opt,
                               loss_fn=paddle.nn.CrossEntropyLoss(),
                               donate=donate)

    def peak(donate):
        e = make_big(donate)
        comp = e._build(arrays).lower(
            e.params, e.opt_state, jnp.float32(0.01), jnp.int32(1),
            jax.random.key(0), *arrays).compile()
        ma = comp.memory_analysis()
        state = sum(int(np.prod(t.shape) or 1) * 4
                    for t in e.params.values())
        state += sum(int(np.prod(s.shape) or 1) * 4
                     for st in e.opt_state.values() for s in st)
        p = int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                - ma.alias_size_in_bytes + ma.temp_size_in_bytes)
        return p, int(ma.alias_size_in_bytes), state

    peak_on, alias_on, state = peak(True)
    peak_off, alias_off, _ = peak(False)
    assert alias_off == 0
    assert alias_on >= 0.9 * state, (
        f"donation aliasing regressed: alias {alias_on} < state {state}")
    # the peak itself also drops, though by less than the full state on a
    # toy model (temp scheduling differs between the two compilations)
    assert peak_off - peak_on >= 0.5 * state, (
        f"donation no longer removes the state copy: peak {peak_off} -> "
        f"{peak_on}, state {state}")


def test_accum_step_donation_and_engine_paths():
    """The microbatch-accumulation step (grad_comm) donates exactly like
    the single-shot step: stale pre-step trees are deleted, every
    engine-owned path stays clean, and mixing accumulated and plain steps
    never observes a donated buffer."""
    e = _make()
    e.microbatches = 2
    x, y = _batch()
    stale = dict(e.params)
    e.step(x, y)                     # accumulation path (K=2)
    name = next(iter(stale))
    assert stale[name].is_deleted()
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(stale[name])
    assert np.isfinite(np.asarray(e.params[name])).all()
    e.microbatches = 1
    e.step(x, y)                     # plain path on the same engine
    e.microbatches = 4
    e.step(x, y)                     # new accumulation variant
    sd = e.state_dict()
    for t in sd.values():
        assert np.isfinite(t.numpy()).all()
    e.sync_to_model()
    for p in e.model.parameters():
        assert np.isfinite(p.numpy()).all()


def test_accum_error_feedback_residual_is_donated():
    """With error feedback on, the residual buffer is carried state: the
    step donates it, rebinds the fresh one, and the live-buffer census
    stays flat across steps (no residual copies accumulate)."""
    import paddle_tpu

    paddle_tpu.set_flags({"grad_comm_dtype": "int8",
                          "grad_comm_error_feedback": True})
    e = _make()
    e.microbatches = 2
    tele = e.enable_telemetry(collect_live_buffers=True)
    x, y = _batch()
    e.step(x, y)
    stale_res = e._grad_residual
    first = tele.sink.records[0]["live_buffers"]
    for _ in range(3):
        e.step(x, y)
    assert stale_res.is_deleted()    # donated into the next step
    assert not e._grad_residual.is_deleted()
    last = tele.sink.records[-1]["live_buffers"]
    assert last["high_water_bytes"] <= first["bytes"] * 1.05, (
        "live-buffer high-water grew across error-feedback steps: residual "
        "or state copies are being retained")


def test_step_telemetry_live_buffer_high_water_stays_flat():
    """With donation on, the per-step live-array census must not grow: the
    update is in place, so N steps hold one copy of the training state (a
    growing high-water here means donated trees are being retained)."""
    e = _make()
    tele = e.enable_telemetry(collect_live_buffers=True)
    x, y = _batch()
    e.step(x, y)
    first = tele.sink.records[0]["live_buffers"]
    assert first["count"] > 0 and first["bytes"] > 0
    for _ in range(4):
        e.step(x, y)
    last = tele.sink.records[-1]["live_buffers"]
    assert last["high_water_bytes"] <= first["bytes"] * 1.05, (
        "live-buffer high-water grew across donated steps: a stale copy of "
        "params/opt state is being kept alive")


def test_static_executor_donation_toggle():
    """The static train program donates by default; donate=False keeps the
    pre-step capture buffers alive (and the two runs agree numerically)."""
    import paddle_tpu.static as static

    def run(donate):
        paddle.seed(0)
        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [4, 8], "float32")
                yv = static.data("yv", [4, 1], "float32")
                lin = paddle.nn.Linear(8, 1)
                loss = ((lin(x) - yv) ** 2).mean()
                opt = paddle.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)
            exe = static.Executor(donate=donate)
            exe.run(startup)
            before = {n: main._captures[n]._data
                      for n in main.parameters()}
            rng = np.random.RandomState(0)
            out = exe.run(main,
                          feed={"x": rng.randn(4, 8).astype(np.float32),
                                "yv": rng.randn(4, 1).astype(np.float32)},
                          fetch_list=[loss])
            deleted = {n: a.is_deleted() for n, a in before.items()}
            return out[0], deleted
        finally:
            paddle.disable_static()

    loss_d, deleted_d = run(True)
    loss_k, deleted_k = run(False)
    np.testing.assert_array_equal(loss_d, loss_k)
    assert all(deleted_d.values())   # donated: stale captures are gone
    assert not any(deleted_k.values())

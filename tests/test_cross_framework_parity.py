"""Model-level cross-framework parity: the same tiny GPT built independently
in torch (CPU reference implementation) with weights copied across must
produce the same logits, loss, and parameter gradients.

This is the reference's OpTest philosophy (numpy reference per op,
unittests/op_test.py:289) lifted to model granularity with a STRONGER
reference: a complete independent framework. It pins the whole composition —
embedding + causal attention + GELU MLP + pre-LN residuals + weight-tied
LM head + masked mean CE — not just individual kernels.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForPretraining

B, S, V, H, L, NH = 2, 16, 128, 32, 2, 4


class TorchGPT(torch.nn.Module):
    """Independent torch implementation of the same architecture."""

    def __init__(self):
        super().__init__()
        self.wte = torch.nn.Embedding(V, H)
        self.wpe = torch.nn.Embedding(S, H)
        self.ln1 = torch.nn.ModuleList(
            [torch.nn.LayerNorm(H) for _ in range(L)])
        self.ln2 = torch.nn.ModuleList(
            [torch.nn.LayerNorm(H) for _ in range(L)])
        self.qkv = torch.nn.ModuleList(
            [torch.nn.Linear(H, 3 * H) for _ in range(L)])
        self.proj = torch.nn.ModuleList(
            [torch.nn.Linear(H, H) for _ in range(L)])
        self.fc1 = torch.nn.ModuleList(
            [torch.nn.Linear(H, 4 * H) for _ in range(L)])
        self.fc2 = torch.nn.ModuleList(
            [torch.nn.Linear(4 * H, H) for _ in range(L)])
        self.ln_f = torch.nn.LayerNorm(H)

    def forward(self, ids):
        b, s = ids.shape
        x = self.wte(ids) + self.wpe(torch.arange(s))
        for i in range(L):
            h = self.ln1[i](x)
            qkv = self.qkv[i](h).view(b, s, 3, NH, H // NH)
            q, k, v = qkv.unbind(2)
            o = torch.nn.functional.scaled_dot_product_attention(
                q.transpose(1, 2), k.transpose(1, 2), v.transpose(1, 2),
                is_causal=True)
            x = x + self.proj[i](
                o.transpose(1, 2).reshape(b, s, H))
            x = x + self.fc2[i](torch.nn.functional.gelu(
                self.fc1[i](self.ln2[i](x)), approximate="tanh"))
        h = self.ln_f(x)
        return h @ self.wte.weight.t()  # tied head


def _numpy_state_dict(pm):
    return {k: np.array(v.numpy()) for k, v in pm.state_dict().items()}


def _copy_weights(pm, tm):
    """paddle_tpu state_dict -> torch parameters (same layouts: our Linear
    stores [in, out], torch stores [out, in])."""
    sd = _numpy_state_dict(pm)
    with torch.no_grad():
        tm.wte.weight.copy_(torch.from_numpy(sd["gpt.wte.weight"]))
        tm.wpe.weight.copy_(torch.from_numpy(sd["gpt.wpe.weight"]))
        tm.ln_f.weight.copy_(torch.from_numpy(sd["gpt.ln_f.weight"]))
        tm.ln_f.bias.copy_(torch.from_numpy(sd["gpt.ln_f.bias"]))
        for i in range(L):
            p = f"gpt.blocks.{i}."
            tm.ln1[i].weight.copy_(torch.from_numpy(sd[p + "ln1.weight"]))
            tm.ln1[i].bias.copy_(torch.from_numpy(sd[p + "ln1.bias"]))
            tm.ln2[i].weight.copy_(torch.from_numpy(sd[p + "ln2.weight"]))
            tm.ln2[i].bias.copy_(torch.from_numpy(sd[p + "ln2.bias"]))
            tm.qkv[i].weight.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.weight"].T))
            tm.qkv[i].bias.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.bias"]))
            tm.proj[i].weight.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.weight"].T))
            tm.proj[i].bias.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.bias"]))
            tm.fc1[i].weight.copy_(
                torch.from_numpy(sd[p + "mlp.fc1.weight"].T))
            tm.fc1[i].bias.copy_(torch.from_numpy(sd[p + "mlp.fc1.bias"]))
            tm.fc2[i].weight.copy_(
                torch.from_numpy(sd[p + "mlp.fc2.weight"].T))
            tm.fc2[i].bias.copy_(torch.from_numpy(sd[p + "mlp.fc2.bias"]))


def _fresh_pair():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L, num_heads=NH,
                    max_seq_len=S)
    pm = GPTForPretraining(cfg)
    pm.eval()
    tm = TorchGPT()
    tm.eval()
    _copy_weights(pm, tm)
    ids = np.random.RandomState(0).randint(0, V, (B, S)).astype(np.int64)
    return pm, tm, ids


@pytest.fixture(scope="module")
def models():
    return _fresh_pair()


def test_logits_parity(models):
    pm, tm, ids = models
    ours = pm.logits(paddle.to_tensor(ids)).numpy()
    theirs = tm(torch.from_numpy(ids)).detach().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_loss_and_grad_parity(models):
    pm, tm, ids = models
    labels = np.roll(ids, -1, 1)

    pm.train()
    loss_p = pm(paddle.to_tensor(ids), paddle.to_tensor(labels))
    loss_p.backward()
    g_wte_p = pm.gpt.wte.weight.grad.numpy()
    g_fc1_p = pm.gpt.blocks[0].mlp.fc1.weight.grad.numpy()
    pm.eval()

    tm.train()
    logits_t = tm(torch.from_numpy(ids))
    loss_t = torch.nn.functional.cross_entropy(
        logits_t.reshape(-1, V), torch.from_numpy(labels).reshape(-1))
    loss_t.backward()
    tm.eval()

    np.testing.assert_allclose(float(loss_p.item()),
                               float(loss_t.item()), rtol=1e-4)
    np.testing.assert_allclose(g_wte_p, tm.wte.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(g_fc1_p, tm.fc1[0].weight.grad.numpy().T,
                               rtol=3e-4, atol=3e-5)


class TorchCNN(torch.nn.Module):
    """Independent torch twin of the paddle_tpu CNN below (OIHW conv weights
    in both frameworks; BN in train mode uses batch statistics)."""

    def __init__(self):
        super().__init__()
        self.c1 = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1)
        self.bn = torch.nn.BatchNorm2d(8)
        self.c2 = torch.nn.Conv2d(8, 16, 3, groups=2)
        self.fc = torch.nn.Linear(16, 5)

    def forward(self, x):
        h = torch.relu(self.bn(self.c1(x)))
        h = torch.nn.functional.max_pool2d(h, 2)
        h = torch.relu(self.c2(h))
        h = h.mean(dim=(2, 3))
        return self.fc(h)


def test_vision_stack_parity():
    """Conv (strided, padded, grouped) + BatchNorm + pooling + Linear:
    forward and gradient parity against torch pins the NCHW layout and
    padding conventions of the whole vision stack."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as PF

    paddle.seed(0)

    class OursCNN(nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2D(3, 8, 3, stride=2, padding=1)
            self.bn = nn.BatchNorm2D(8)
            self.c2 = nn.Conv2D(8, 16, 3, groups=2)
            self.fc = nn.Linear(16, 5)

        def forward(self, x):
            h = PF.relu(self.bn(self.c1(x)))
            h = PF.max_pool2d(h, 2)
            h = PF.relu(self.c2(h))
            h = h.mean(axis=[2, 3])
            return self.fc(h)

    pm = OursCNN()
    tm = TorchCNN()
    sd = _numpy_state_dict(pm)
    with torch.no_grad():
        tm.c1.weight.copy_(torch.from_numpy(sd["c1.weight"]))
        tm.c1.bias.copy_(torch.from_numpy(sd["c1.bias"]))
        tm.bn.weight.copy_(torch.from_numpy(sd["bn.weight"]))
        tm.bn.bias.copy_(torch.from_numpy(sd["bn.bias"]))
        tm.c2.weight.copy_(torch.from_numpy(sd["c2.weight"]))
        tm.c2.bias.copy_(torch.from_numpy(sd["c2.bias"]))
        tm.fc.weight.copy_(torch.from_numpy(sd["fc.weight"].T))
        tm.fc.bias.copy_(torch.from_numpy(sd["fc.bias"]))

    x = np.random.RandomState(0).randn(4, 3, 16, 16).astype("float32")
    pm.train()
    tm.train()
    out_p = pm(paddle.to_tensor(x))
    out_t = tm(torch.from_numpy(x))
    np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                               rtol=2e-4, atol=2e-5)

    out_p.sum().backward()
    out_t.sum().backward()
    np.testing.assert_allclose(pm.c1.weight.grad.numpy(),
                               tm.c1.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(pm.c2.weight.grad.numpy(),
                               tm.c2.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(pm.bn.weight.grad.numpy(),
                               tm.bn.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)


def test_ernie_encoder_parity():
    """Post-LN bidirectional encoder (ERNIE/BERT convention) with
    word+position+type embeddings, additive attention mask, and tanh pooler
    matches an independent torch twin on sequence output and pooled output."""
    from paddle_tpu.models import ErnieConfig, ErnieModel

    EV, EH, EL, ENH, ES = 64, 32, 2, 4, 12
    paddle.seed(0)
    cfg = ErnieConfig(vocab_size=EV, hidden_size=EH, num_layers=EL,
                      num_heads=ENH, max_seq_len=ES, dropout=0.0,
                      attention_dropout=0.0)
    pm = ErnieModel(cfg)
    pm.eval()

    class TorchErnie(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.word = torch.nn.Embedding(EV, EH)
            self.pos = torch.nn.Embedding(ES, EH)
            self.typ = torch.nn.Embedding(cfg.type_vocab_size, EH)
            self.emb_ln = torch.nn.LayerNorm(EH)
            mk = lambda: torch.nn.ModuleDict({
                "qkv": torch.nn.Linear(EH, 3 * EH),
                "proj": torch.nn.Linear(EH, EH),
                "ln1": torch.nn.LayerNorm(EH),
                "fc1": torch.nn.Linear(EH, cfg.ffn_hidden_size),
                "fc2": torch.nn.Linear(cfg.ffn_hidden_size, EH),
                "ln2": torch.nn.LayerNorm(EH)})
            self.blocks = torch.nn.ModuleList([mk() for _ in range(EL)])
            self.pooler = torch.nn.Linear(EH, EH)

        def forward(self, ids, type_ids, mask):
            b, s = ids.shape
            x = self.word(ids) + self.pos(torch.arange(s)) + self.typ(type_ids)
            x = self.emb_ln(x)
            amask = (1.0 - mask.float()) * -1e4  # additive [b,1,1,s]
            amask = amask.view(b, 1, 1, s)
            for blk in self.blocks:
                qkv = blk["qkv"](x).view(b, s, 3, ENH, EH // ENH)
                q, k, v = qkv.unbind(2)
                o = torch.nn.functional.scaled_dot_product_attention(
                    q.transpose(1, 2), k.transpose(1, 2), v.transpose(1, 2),
                    attn_mask=amask)
                h = blk["ln1"](x + blk["proj"](
                    o.transpose(1, 2).reshape(b, s, EH)))
                ffn = blk["fc2"](torch.nn.functional.gelu(
                    blk["fc1"](h), approximate="tanh"))
                x = blk["ln2"](h + ffn)
            return x, torch.tanh(self.pooler(x[:, 0]))

    tm = TorchErnie()
    tm.eval()
    sd = _numpy_state_dict(pm)
    with torch.no_grad():
        tm.word.weight.copy_(torch.from_numpy(sd["word_emb.weight"]))
        tm.pos.weight.copy_(torch.from_numpy(sd["pos_emb.weight"]))
        tm.typ.weight.copy_(torch.from_numpy(sd["type_emb.weight"]))
        tm.emb_ln.weight.copy_(torch.from_numpy(sd["emb_ln.weight"]))
        tm.emb_ln.bias.copy_(torch.from_numpy(sd["emb_ln.bias"]))
        tm.pooler.weight.copy_(torch.from_numpy(sd["pooler.weight"].T))
        tm.pooler.bias.copy_(torch.from_numpy(sd["pooler.bias"]))
        for i in range(EL):
            p = f"blocks.{i}."
            b = tm.blocks[i]
            b["qkv"].weight.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.weight"].T))
            b["qkv"].bias.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.bias"]))
            b["proj"].weight.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.weight"].T))
            b["proj"].bias.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.bias"]))
            for nm in ("ln1", "ln2"):
                b[nm].weight.copy_(torch.from_numpy(sd[p + nm + ".weight"]))
                b[nm].bias.copy_(torch.from_numpy(sd[p + nm + ".bias"]))
            b["fc1"].weight.copy_(torch.from_numpy(sd[p + "fc1.weight"].T))
            b["fc1"].bias.copy_(torch.from_numpy(sd[p + "fc1.bias"]))
            b["fc2"].weight.copy_(torch.from_numpy(sd[p + "fc2.weight"].T))
            b["fc2"].bias.copy_(torch.from_numpy(sd[p + "fc2.bias"]))

    rng = np.random.RandomState(2)
    ids = rng.randint(0, EV, (2, ES)).astype(np.int64)
    type_ids = rng.randint(0, 2, (2, ES)).astype(np.int64)
    mask = np.ones((2, ES), np.int64)
    mask[:, -3:] = 0  # padded tail

    seq_p, pool_p = pm(paddle.to_tensor(ids), paddle.to_tensor(type_ids),
                       paddle.to_tensor(mask))
    seq_t, pool_t = tm(torch.from_numpy(ids), torch.from_numpy(type_ids),
                       torch.from_numpy(mask))
    np.testing.assert_allclose(seq_p.numpy(), seq_t.detach().numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(pool_p.numpy(), pool_t.detach().numpy(),
                               rtol=3e-4, atol=3e-5)


def test_lstm_parity():
    """The fused-scan LSTM (nn/layers/rnn.py) matches torch.nn.LSTM on
    outputs and final (h, c) with copied gate weights (both use the
    i,f,g,o gate order and [4h, in] weight layout)."""
    import paddle_tpu.nn as nn

    IN, HID, T, BT = 6, 8, 5, 3
    paddle.seed(0)
    pm = nn.LSTM(IN, HID, num_layers=1)
    tm = torch.nn.LSTM(IN, HID, num_layers=1, batch_first=True)
    sd = _numpy_state_dict(pm)
    pre = "_all_layers.0.cell."
    with torch.no_grad():
        tm.weight_ih_l0.copy_(torch.from_numpy(sd[pre + "weight_ih"]))
        tm.weight_hh_l0.copy_(torch.from_numpy(sd[pre + "weight_hh"]))
        tm.bias_ih_l0.copy_(torch.from_numpy(sd[pre + "bias_ih"]))
        tm.bias_hh_l0.copy_(torch.from_numpy(sd[pre + "bias_hh"]))

    x = np.random.RandomState(3).randn(BT, T, IN).astype("float32")
    out_p, (h_p, c_p) = pm(paddle.to_tensor(x))
    out_t, (h_t, c_t) = tm(torch.from_numpy(x))
    np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(h_p.numpy(), h_t.detach().numpy(),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(c_p.numpy(), c_t.detach().numpy(),
                               rtol=2e-4, atol=2e-5)

    # gradient parity through the fused lax.scan vjp (rnn.py's TPU-first
    # backward) vs torch's autograd through its unrolled loop
    out_p.sum().backward()
    out_t.sum().backward()
    cell = pm._all_layers[0].cell
    np.testing.assert_allclose(cell.weight_ih.grad.numpy(),
                               tm.weight_ih_l0.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(cell.weight_hh.grad.numpy(),
                               tm.weight_hh_l0.grad.numpy(),
                               rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("opt_name", ["adamw", "sgd_momentum"])
def test_optimizer_trajectory_parity(opt_name):
    """Five full training steps must track torch step-for-step: same loss at
    every step and same parameters at the end. Pins the optimizer update
    rules (decoupled AdamW weight decay, classical momentum) composed with
    the full model's gradients, not just per-op math. Builds a FRESH model
    pair: this test mutates weights, and the shared fixture must stay
    pristine under shuffled test order."""
    pm, tm, ids = _fresh_pair()
    labels = np.roll(ids, -1, 1)

    if opt_name == "adamw":
        opt_p = paddle.optimizer.AdamW(learning_rate=1e-3,
                                       parameters=pm.parameters(),
                                       weight_decay=0.01)
        opt_t = torch.optim.AdamW(tm.parameters(), lr=1e-3, weight_decay=0.01)
    else:
        opt_p = paddle.optimizer.Momentum(learning_rate=1e-2,
                                          momentum=0.9,
                                          parameters=pm.parameters())
        opt_t = torch.optim.SGD(tm.parameters(), lr=1e-2, momentum=0.9)

    pm.train()
    tm.train()
    losses_p, losses_t = [], []
    for _ in range(5):
        loss_p = pm(paddle.to_tensor(ids), paddle.to_tensor(labels))
        loss_p.backward()
        opt_p.step()
        opt_p.clear_grad()
        losses_p.append(float(loss_p.item()))

        opt_t.zero_grad()
        logits = tm(torch.from_numpy(ids))
        loss_t = torch.nn.functional.cross_entropy(
            logits.reshape(-1, V), torch.from_numpy(labels).reshape(-1))
        loss_t.backward()
        opt_t.step()
        losses_t.append(float(loss_t.item()))
    pm.eval()

    np.testing.assert_allclose(losses_p, losses_t, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        pm.gpt.blocks[0].mlp.fc1.weight.numpy(),
        tm.fc1[0].weight.detach().numpy().T, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        pm.gpt.wte.weight.numpy(), tm.wte.weight.detach().numpy(),
        rtol=2e-4, atol=2e-5)


def test_conv_variants_parity():
    """Conv1D, Conv3D, and Conv2DTranspose (incl. output_padding and
    stride) vs torch: layouts and transposed-conv conventions pinned."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    x1 = np.random.RandomState(0).randn(2, 3, 20).astype("float32")
    c1 = nn.Conv1D(3, 5, 4, stride=2, padding=1)
    t1 = torch.nn.Conv1d(3, 5, 4, stride=2, padding=1)
    with torch.no_grad():
        t1.weight.copy_(torch.from_numpy(np.array(c1.weight.numpy())))
        t1.bias.copy_(torch.from_numpy(np.array(c1.bias.numpy())))
    np.testing.assert_allclose(c1(paddle.to_tensor(x1)).numpy(),
                               t1(torch.from_numpy(x1)).detach().numpy(),
                               rtol=2e-4, atol=2e-5)

    x3 = np.random.RandomState(1).randn(1, 2, 6, 6, 6).astype("float32")
    c3 = nn.Conv3D(2, 4, 3, padding=1)
    t3 = torch.nn.Conv3d(2, 4, 3, padding=1)
    with torch.no_grad():
        t3.weight.copy_(torch.from_numpy(np.array(c3.weight.numpy())))
        t3.bias.copy_(torch.from_numpy(np.array(c3.bias.numpy())))
    np.testing.assert_allclose(c3(paddle.to_tensor(x3)).numpy(),
                               t3(torch.from_numpy(x3)).detach().numpy(),
                               rtol=2e-4, atol=2e-5)

    xt = np.random.RandomState(2).randn(2, 4, 5, 5).astype("float32")
    ct = nn.Conv2DTranspose(4, 3, 3, stride=2, padding=1, output_padding=1)
    tt = torch.nn.ConvTranspose2d(4, 3, 3, stride=2, padding=1,
                                  output_padding=1)
    with torch.no_grad():
        tt.weight.copy_(torch.from_numpy(np.array(ct.weight.numpy())))
        tt.bias.copy_(torch.from_numpy(np.array(ct.bias.numpy())))
    ours = ct(paddle.to_tensor(xt)).numpy()
    ref = tt(torch.from_numpy(xt)).detach().numpy()
    assert ours.shape == ref.shape == (2, 3, 10, 10)
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-5)

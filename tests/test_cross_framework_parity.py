"""Model-level cross-framework parity: the same tiny GPT built independently
in torch (CPU reference implementation) with weights copied across must
produce the same logits, loss, and parameter gradients.

This is the reference's OpTest philosophy (numpy reference per op,
unittests/op_test.py:289) lifted to model granularity with a STRONGER
reference: a complete independent framework. It pins the whole composition —
embedding + causal attention + GELU MLP + pre-LN residuals + weight-tied
LM head + masked mean CE — not just individual kernels.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForPretraining

B, S, V, H, L, NH = 2, 16, 128, 32, 2, 4


class TorchGPT(torch.nn.Module):
    """Independent torch implementation of the same architecture."""

    def __init__(self):
        super().__init__()
        self.wte = torch.nn.Embedding(V, H)
        self.wpe = torch.nn.Embedding(S, H)
        self.ln1 = torch.nn.ModuleList(
            [torch.nn.LayerNorm(H) for _ in range(L)])
        self.ln2 = torch.nn.ModuleList(
            [torch.nn.LayerNorm(H) for _ in range(L)])
        self.qkv = torch.nn.ModuleList(
            [torch.nn.Linear(H, 3 * H) for _ in range(L)])
        self.proj = torch.nn.ModuleList(
            [torch.nn.Linear(H, H) for _ in range(L)])
        self.fc1 = torch.nn.ModuleList(
            [torch.nn.Linear(H, 4 * H) for _ in range(L)])
        self.fc2 = torch.nn.ModuleList(
            [torch.nn.Linear(4 * H, H) for _ in range(L)])
        self.ln_f = torch.nn.LayerNorm(H)

    def forward(self, ids):
        b, s = ids.shape
        x = self.wte(ids) + self.wpe(torch.arange(s))
        for i in range(L):
            h = self.ln1[i](x)
            qkv = self.qkv[i](h).view(b, s, 3, NH, H // NH)
            q, k, v = qkv.unbind(2)
            o = torch.nn.functional.scaled_dot_product_attention(
                q.transpose(1, 2), k.transpose(1, 2), v.transpose(1, 2),
                is_causal=True)
            x = x + self.proj[i](
                o.transpose(1, 2).reshape(b, s, H))
            x = x + self.fc2[i](torch.nn.functional.gelu(
                self.fc1[i](self.ln2[i](x)), approximate="tanh"))
        h = self.ln_f(x)
        return h @ self.wte.weight.t()  # tied head


def _copy_weights(pm, tm):
    """paddle_tpu state_dict -> torch parameters (same layouts: our Linear
    stores [in, out], torch stores [out, in])."""
    sd = {k: np.array(v.numpy()) for k, v in pm.state_dict().items()}
    with torch.no_grad():
        tm.wte.weight.copy_(torch.from_numpy(sd["gpt.wte.weight"]))
        tm.wpe.weight.copy_(torch.from_numpy(sd["gpt.wpe.weight"]))
        tm.ln_f.weight.copy_(torch.from_numpy(sd["gpt.ln_f.weight"]))
        tm.ln_f.bias.copy_(torch.from_numpy(sd["gpt.ln_f.bias"]))
        for i in range(L):
            p = f"gpt.blocks.{i}."
            tm.ln1[i].weight.copy_(torch.from_numpy(sd[p + "ln1.weight"]))
            tm.ln1[i].bias.copy_(torch.from_numpy(sd[p + "ln1.bias"]))
            tm.ln2[i].weight.copy_(torch.from_numpy(sd[p + "ln2.weight"]))
            tm.ln2[i].bias.copy_(torch.from_numpy(sd[p + "ln2.bias"]))
            tm.qkv[i].weight.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.weight"].T))
            tm.qkv[i].bias.copy_(
                torch.from_numpy(sd[p + "attn.qkv_proj.bias"]))
            tm.proj[i].weight.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.weight"].T))
            tm.proj[i].bias.copy_(
                torch.from_numpy(sd[p + "attn.out_proj.bias"]))
            tm.fc1[i].weight.copy_(
                torch.from_numpy(sd[p + "mlp.fc1.weight"].T))
            tm.fc1[i].bias.copy_(torch.from_numpy(sd[p + "mlp.fc1.bias"]))
            tm.fc2[i].weight.copy_(
                torch.from_numpy(sd[p + "mlp.fc2.weight"].T))
            tm.fc2[i].bias.copy_(torch.from_numpy(sd[p + "mlp.fc2.bias"]))


@pytest.fixture(scope="module")
def models():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=V, hidden_size=H, num_layers=L, num_heads=NH,
                    max_seq_len=S)
    pm = GPTForPretraining(cfg)
    pm.eval()
    tm = TorchGPT()
    tm.eval()
    _copy_weights(pm, tm)
    ids = np.random.RandomState(0).randint(0, V, (B, S)).astype(np.int64)
    return pm, tm, ids


def test_logits_parity(models):
    pm, tm, ids = models
    ours = pm.logits(paddle.to_tensor(ids)).numpy()
    theirs = tm(torch.from_numpy(ids)).detach().numpy()
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_loss_and_grad_parity(models):
    pm, tm, ids = models
    labels = np.roll(ids, -1, 1)

    pm.train()
    loss_p = pm(paddle.to_tensor(ids), paddle.to_tensor(labels))
    loss_p.backward()
    g_wte_p = pm.gpt.wte.weight.grad.numpy()
    g_fc1_p = pm.gpt.blocks[0].mlp.fc1.weight.grad.numpy()
    pm.eval()

    tm.train()
    logits_t = tm(torch.from_numpy(ids))
    loss_t = torch.nn.functional.cross_entropy(
        logits_t.reshape(-1, V), torch.from_numpy(labels).reshape(-1))
    loss_t.backward()
    tm.eval()

    np.testing.assert_allclose(float(loss_p.item()),
                               float(loss_t.item()), rtol=1e-4)
    np.testing.assert_allclose(g_wte_p, tm.wte.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(g_fc1_p, tm.fc1[0].weight.grad.numpy().T,
                               rtol=3e-4, atol=3e-5)


class TorchCNN(torch.nn.Module):
    """Independent torch twin of the paddle_tpu CNN below (OIHW conv weights
    in both frameworks; BN in train mode uses batch statistics)."""

    def __init__(self):
        super().__init__()
        self.c1 = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1)
        self.bn = torch.nn.BatchNorm2d(8)
        self.c2 = torch.nn.Conv2d(8, 16, 3, groups=2)
        self.fc = torch.nn.Linear(16, 5)

    def forward(self, x):
        h = torch.relu(self.bn(self.c1(x)))
        h = torch.nn.functional.max_pool2d(h, 2)
        h = torch.relu(self.c2(h))
        h = h.mean(dim=(2, 3))
        return self.fc(h)


def test_vision_stack_parity():
    """Conv (strided, padded, grouped) + BatchNorm + pooling + Linear:
    forward and gradient parity against torch pins the NCHW layout and
    padding conventions of the whole vision stack."""
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as PF

    paddle.seed(0)

    class OursCNN(nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2D(3, 8, 3, stride=2, padding=1)
            self.bn = nn.BatchNorm2D(8)
            self.c2 = nn.Conv2D(8, 16, 3, groups=2)
            self.fc = nn.Linear(16, 5)

        def forward(self, x):
            h = PF.relu(self.bn(self.c1(x)))
            h = PF.max_pool2d(h, 2)
            h = PF.relu(self.c2(h))
            h = h.mean(axis=[2, 3])
            return self.fc(h)

    pm = OursCNN()
    tm = TorchCNN()
    sd = {k: np.array(v.numpy()) for k, v in pm.state_dict().items()}
    with torch.no_grad():
        tm.c1.weight.copy_(torch.from_numpy(sd["c1.weight"]))
        tm.c1.bias.copy_(torch.from_numpy(sd["c1.bias"]))
        tm.bn.weight.copy_(torch.from_numpy(sd["bn.weight"]))
        tm.bn.bias.copy_(torch.from_numpy(sd["bn.bias"]))
        tm.c2.weight.copy_(torch.from_numpy(sd["c2.weight"]))
        tm.c2.bias.copy_(torch.from_numpy(sd["c2.bias"]))
        tm.fc.weight.copy_(torch.from_numpy(sd["fc.weight"].T))
        tm.fc.bias.copy_(torch.from_numpy(sd["fc.bias"]))

    x = np.random.RandomState(0).randn(4, 3, 16, 16).astype("float32")
    pm.train()
    tm.train()
    out_p = pm(paddle.to_tensor(x))
    out_t = tm(torch.from_numpy(x))
    np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                               rtol=2e-4, atol=2e-5)

    out_p.sum().backward()
    out_t.sum().backward()
    np.testing.assert_allclose(pm.c1.weight.grad.numpy(),
                               tm.c1.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(pm.c2.weight.grad.numpy(),
                               tm.c2.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(pm.bn.weight.grad.numpy(),
                               tm.bn.weight.grad.numpy(),
                               rtol=3e-4, atol=3e-5)

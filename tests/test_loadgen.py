"""Replayable load scenarios (ISSUE 16 tentpole a).

Pinned contracts:
- byte-identity: the same scenario + seed compiles to the same
  schedule_doc() bytes across runs, across a dumps/loads round-trip,
  across a scenario-file save/load round-trip, and across interpreter
  hash seeds (string-seeded random.Random uses sha512, not hash());
- the seed is the only entropy source: changing it changes the schedule,
  changing nothing keeps every row;
- length dists: fixed is constant, cycle is values[i % n] exactly (and
  consumes no randomness — swapping it for fixed leaves every other draw
  untouched), lognormal respects min/max clamps, choice draws only from
  its value set;
- arrival processes: batch puts count rows at t=0, spike labels the
  window "spike" and raises its arrival density, diurnal labels
  peak/trough, rates beyond MAX_EVENTS fail loudly;
- zipf tenant skew shows up in the schedule (first tenant dominates);
- LoadGenerator drives a schedule open-loop in arrival order, threads
  tenants through submit, and reduces the episode to a summary doc.
"""
import json
import os
import subprocess
import sys
import types

import pytest

from paddle_tpu.serving.loadgen import (
    LoadGenerator, Scenario, spike_scenario, zipf_tenants,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mixed_scenario(seed=3):
    return Scenario(
        name="mixed", seed=seed, duration_s=8.0,
        arrival={"process": "poisson", "rate_rps": 5.0},
        prompt_len={"dist": "lognormal", "median": 8, "sigma": 0.6,
                    "min": 2, "max": 32},
        max_new={"dist": "choice", "values": [2, 4, 8],
                 "weights": [4, 2, 1]},
        tenants=zipf_tenants(4))


# ----------------------------------------------------------- byte-identity

def test_schedule_byte_identical_across_runs_and_round_trips(tmp_path):
    scn = _mixed_scenario()
    doc = scn.schedule_doc()
    assert doc == _mixed_scenario().schedule_doc()          # fresh object
    assert doc == Scenario.loads(scn.dumps()).schedule_doc()  # json twin
    p = scn.save(str(tmp_path / "mixed.json"))
    assert doc == Scenario.load(p).schedule_doc()           # file twin
    # canonical JSON: compact separators, sorted keys, parseable
    parsed = json.loads(doc)
    assert parsed["scenario"] == "mixed" and parsed["seed"] == 3
    assert doc == json.dumps(parsed, sort_keys=True,
                             separators=(",", ":"))


def test_schedule_survives_interpreter_hash_seed(tmp_path):
    """String-seeded random.Random hashes via sha512 — PYTHONHASHSEED
    must not leak into the schedule. loadgen is stdlib-only, so the
    subprocess loads the module file directly (no jax import)."""
    prog = (
        "import importlib.util, sys\n"
        "spec = importlib.util.spec_from_file_location('lg', sys.argv[1])\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "print(m.spike_scenario().schedule_doc())\n")
    path = os.path.join(_REPO, "paddle_tpu", "serving", "loadgen.py")
    docs = []
    for hash_seed in ("0", "12345"):
        env = {**os.environ, "PYTHONHASHSEED": hash_seed}
        out = subprocess.run([sys.executable, "-c", prog, path], env=env,
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        docs.append(out.stdout.strip())
    assert docs[0] == docs[1]
    assert docs[0] == spike_scenario().schedule_doc()


def test_seed_is_the_only_entropy_source():
    a, b = _mixed_scenario(seed=3), _mixed_scenario(seed=4)
    assert a.schedule_doc() != b.schedule_doc()
    rows = a.schedule()
    assert rows == _mixed_scenario(seed=3).schedule()
    assert [r["i"] for r in rows] == list(range(len(rows)))
    assert all(0.0 <= r["t"] < a.duration_s for r in rows)
    assert rows == sorted(rows, key=lambda r: r["t"])


# ------------------------------------------------------------ length dists

def test_cycle_dist_is_positional_and_draws_nothing():
    values = [3, 5, 7]
    cyc = Scenario(name="c", seed=1, arrival={"process": "batch",
                                              "count": 9},
                   prompt_len={"dist": "cycle", "values": values})
    lens = [r["prompt_len"] for r in cyc.schedule()]
    assert lens == [values[i % 3] for i in range(9)]
    # cycle consumes no randomness: swapping it for fixed leaves the
    # other draws (tenant, max_new) bit-identical
    fix = Scenario(name="c", seed=1, arrival={"process": "batch",
                                              "count": 9},
                   prompt_len={"dist": "fixed", "value": 3})
    strip = [{k: r[k] for k in ("tenant", "max_new")}
             for r in cyc.schedule()]
    assert strip == [{k: r[k] for k in ("tenant", "max_new")}
                     for r in fix.schedule()]


def test_lognormal_clamps_and_choice_stays_in_set():
    scn = _mixed_scenario()
    rows = scn.schedule()
    assert rows, "expected arrivals at 5 rps over 8s"
    assert all(2 <= r["prompt_len"] <= 32 for r in rows)
    assert all(r["max_new"] in (2, 4, 8) for r in rows)
    assert len({r["prompt_len"] for r in rows}) > 3  # actually heavy-tailed


def test_unknown_dist_and_process_fail_loudly():
    with pytest.raises(ValueError, match="arrival process"):
        Scenario(name="x", arrival={"process": "warp"})
    bad = Scenario(name="x", arrival={"process": "batch", "count": 2},
                   prompt_len={"dist": "zeta", "value": 1})
    with pytest.raises(ValueError, match="length dist"):
        bad.schedule()
    # an empty tenant table falls back to the single default tenant
    assert Scenario(name="x", tenants=[]).tenants == \
        [{"name": "default", "weight": 1.0}]
    with pytest.raises(ValueError):
        Scenario(name="x", tenants=[{"name": "t0", "weight": 0.0}])


# ------------------------------------------------------- arrival processes

def test_batch_arrivals_all_at_zero():
    scn = Scenario(name="b", arrival={"process": "batch", "count": 12})
    rows = scn.schedule()
    assert len(rows) == 12
    assert all(r["t"] == 0.0 and r["phase"] == "base" for r in rows)


def test_spike_window_is_denser_and_labeled():
    scn = spike_scenario(duration_s=9.0, rate_rps=4.0, spike_factor=10.0)
    rows = scn.schedule()
    spike = [r for r in rows if r["phase"] == "spike"]
    base = [r for r in rows if r["phase"] == "base"]
    assert spike and base
    assert all(3.0 <= r["t"] < 6.0 for r in spike)  # the middle third
    # 10x the rate over a third of the horizon ≫ the other two thirds
    assert len(spike) > 2 * len(base)


def test_diurnal_phases_and_rate_modulation():
    scn = Scenario(name="d", seed=5, duration_s=10.0,
                   arrival={"process": "diurnal", "rate_rps": 8.0,
                            "period_s": 10.0, "amplitude": 0.9})
    rows = scn.schedule()
    phases = {r["phase"] for r in rows}
    assert phases == {"peak", "trough"}
    peak = sum(r["phase"] == "peak" for r in rows)
    assert peak > (len(rows) - peak)  # sin>0 half carries more arrivals


def test_runaway_rate_raises_instead_of_oom():
    scn = Scenario(name="oops", duration_s=1e9,
                   arrival={"process": "poisson", "rate_rps": 1e6})
    with pytest.raises(ValueError, match="exceeds"):
        scn.schedule()


# ------------------------------------------------------------ tenant skew

def test_zipf_tenants_skew_the_schedule():
    table = zipf_tenants(4, s=1.5)
    assert [t["name"] for t in table] == ["t0", "t1", "t2", "t3"]
    assert table[0]["weight"] > table[1]["weight"] > table[3]["weight"]
    scn = Scenario(name="z", seed=9,
                   arrival={"process": "batch", "count": 400},
                   tenants=table)
    counts = {}
    for r in scn.schedule():
        counts[r["tenant"]] = counts.get(r["tenant"], 0) + 1
    assert counts["t0"] > counts.get("t3", 0)
    assert counts["t0"] > 400 / 4  # above the uniform share


def test_prompt_tokens_deterministic_and_bounded():
    scn = spike_scenario()
    toks = scn.prompt_tokens(5, 12, vocab=64)
    assert toks == scn.prompt_tokens(5, 12, vocab=64)
    assert toks != scn.prompt_tokens(6, 12, vocab=64)
    assert len(toks) == 12 and all(0 <= t < 64 for t in toks)


# ---------------------------------------------------------- LoadGenerator

class _FakeTarget:
    """The submit/step/pending surface LoadGenerator drives; completes
    one request per step (so the drive loop terminates)."""

    def __init__(self):
        self.reqs = []

    def submit(self, prompt_ids, max_new_tokens=None, tenant=None):
        req = types.SimpleNamespace(
            prompt_ids=list(prompt_ids), max_new=max_new_tokens,
            tenant=tenant, done=False, outcome=None,
            ttft_s=0.01, tpot_s=0.002)
        self.reqs.append(req)
        return req

    def step(self):
        for r in self.reqs:
            if not r.done:
                r.done, r.outcome = True, "length"
                return 1
        return 0

    def pending(self):
        return sum(not r.done for r in self.reqs)


def test_loadgen_drives_schedule_in_order_with_tenants():
    scn = spike_scenario(duration_s=4.0, rate_rps=3.0)
    rows = scn.schedule()
    target = _FakeTarget()
    gen = LoadGenerator(scn, target, vocab=64, time_scale=0.0)
    ticks = [0]

    def on_tick():
        ticks[0] += 1

    handles = gen.run(on_tick=on_tick)
    assert len(handles) == len(rows) == len(target.reqs)
    assert [r.tenant for r in target.reqs] == [r["tenant"] for r in rows]
    assert [r.max_new for r in target.reqs] == [r["max_new"] for r in rows]
    assert [len(r.prompt_ids) for r in target.reqs] == \
        [r["prompt_len"] for r in rows]
    assert gen.schedule_ms is not None and gen.schedule_ms >= 0.0
    assert ticks[0] > 0  # the hook rides the drive loop

    s = gen.summary()
    assert s["scenario"] == scn.name and s["requests"] == len(rows)
    assert s["outcomes"] == {"length": len(rows)}
    assert s["good"] == len(rows)
    assert set(s["per_phase"]) == {r["phase"] for r in rows}
    assert sum(s["per_tenant"].values()) == len(rows)
    assert s["per_phase"]["spike"]["p50_ttft_ms"] == pytest.approx(10.0)


def test_loadgen_requires_prompt_source_and_accepts_prompt_fn():
    scn = Scenario(name="p", arrival={"process": "batch", "count": 3})
    with pytest.raises(ValueError, match="prompt_fn or vocab"):
        LoadGenerator(scn, _FakeTarget())
    target = _FakeTarget()
    gen = LoadGenerator(scn, target, time_scale=0.0,
                        prompt_fn=lambda row: [row["i"]] * 2)
    gen.run()
    assert [r.prompt_ids for r in target.reqs] == [[0, 0], [1, 1], [2, 2]]

"""Geo-SGD delta aggregation + GNN graph table (VERDICT r2 #9).

Reference: memory_sparse_geo_table.cc (server ADDS trainer deltas — no
server-side optimizer) and common_graph_table.cc (id-sharded adjacency,
uniform neighbor sampling, node features) — both now real implementations
behind the C++ PS wire protocol, not approximations.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (DenseTableConfig, GeoSync, GraphClient,
                                       GraphTableConfig, PSClient, PSServer,
                                       SparseTableConfig)

pytestmark = pytest.mark.slow  # spins real TCP servers


@pytest.fixture()
def cluster():
    dense = [DenseTableConfig(table_id=1, dim=6)]
    sparse = [SparseTableConfig(table_id=0, dim=4, initial_range=0.0)]
    graph = [GraphTableConfig(table_id=7, feat_dim=3)]
    servers = [PSServer(0, sparse, dense, graph),
               PSServer(0, sparse, dense, graph)]
    clients = [PSClient([f"127.0.0.1:{s.port}" for s in servers])
               for _ in range(2)]
    for c in clients:
        c.register_table_dim(0, 4)
        c.register_table_dim(1, 6)
    yield servers, clients
    for c in clients:
        c.close()
    for s in servers:
        s.stop()


def test_dense_delta_aggregates_across_trainers(cluster):
    """Server param = init + delta_1 + delta_2 — geo-SGD's exact-sum
    aggregation, NOT a server-optimizer step."""
    _, (c1, c2) = cluster
    init = np.arange(6, dtype=np.float32)
    c1.push_dense_param(1, init)
    d1 = np.full(6, 0.5, np.float32)
    d2 = np.asarray([1, -1, 2, -2, 3, -3], np.float32)
    c1.push_dense_delta(1, d1)
    c2.push_dense_delta(1, d2)
    np.testing.assert_allclose(c1.pull_dense(1), init + d1 + d2, rtol=1e-6)


def test_sparse_delta_adds_per_id(cluster):
    _, (c1, c2) = cluster
    ids = np.array([3, 11, 42], np.uint64)
    base = c1.pull_sparse(0, ids)  # zeros (initial_range=0)
    np.testing.assert_allclose(base, 0.0)
    d1 = np.ones((3, 4), np.float32)
    d2 = 2 * np.ones((3, 4), np.float32)
    c1.push_sparse_delta(0, ids, d1)
    c2.push_sparse_delta(0, ids[:1], d2[:1])
    got = c2.pull_sparse(0, ids)
    np.testing.assert_allclose(got[0], 3.0)
    np.testing.assert_allclose(got[1:], 1.0)


def test_geo_sync_two_trainers_converge_to_merged_params(cluster):
    """Two GeoSync trainers optimizing locally: after sync both hold
    init + Δ1 + Δ2 and their local movement is rebased."""
    _, (c1, c2) = cluster
    paddle.seed(0)
    init = np.zeros((2, 3), np.float32)

    def mk(client):
        p = paddle.to_tensor(init.copy())
        p.stop_gradient = False
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
        return p, opt, GeoSync(client, {1: p}, push_interval=2)

    p1, o1, g1 = mk(c1)
    p2, o2, g2 = mk(c2)
    grad1 = paddle.to_tensor(np.ones((2, 3), np.float32))
    grad2 = paddle.to_tensor(np.full((2, 3), 2.0, np.float32))
    for p, o, g, gr in ((p1, o1, g1, grad1), (p2, o2, g2, grad2)):
        for _ in range(2):  # push_interval=2 -> one sync at step 2
            (p * gr).sum().backward()
            o.step()
            o.clear_grad()
            g.step()
    # trainer1 moved by -0.1*1*2 = -0.2, trainer2 by -0.4 per element;
    # trainer1 synced first (delta -0.2), trainer2 saw init-0.2 after its
    # own push: final server param = 0 - 0.2 - 0.4 = -0.6 everywhere
    np.testing.assert_allclose(c1.pull_dense(1), -0.6, rtol=1e-5)
    np.testing.assert_allclose(p2.numpy().reshape(-1), -0.6, rtol=1e-5)
    # trainer1 rebases on its next sync (no local movement -> delta 0)
    g1.sync()
    np.testing.assert_allclose(p1.numpy().reshape(-1), -0.6, rtol=1e-5)


def test_graph_edges_degree_sample(cluster):
    _, (c1, c2) = cluster
    g = GraphClient(c1, table_id=7, feat_dim=3)
    src = np.array([1, 1, 1, 2, 5], np.uint64)
    dst = np.array([10, 11, 12, 20, 50], np.uint64)
    g.add_edges(src, dst)
    np.testing.assert_array_equal(g.degree(np.array([1, 2, 5, 9])),
                                  [3, 1, 1, 0])
    s = g.sample_neighbors(np.array([1, 2, 9]), k=8, seed=123)
    assert s.shape == (3, 8)
    assert set(s[0]) <= {10, 11, 12}
    assert len(set(s[0])) > 1  # uniform over 3 nbrs: 8 draws hit >1
    assert set(s[1]) == {20}
    assert (s[2] == np.iinfo(np.uint64).max).all()  # no neighbors
    # deterministic in seed, different across seeds (statistically)
    s2 = g.sample_neighbors(np.array([1, 2, 9]), k=8, seed=123)
    np.testing.assert_array_equal(s, s2)


def test_graph_features_roundtrip_and_bidirectional(cluster):
    _, (c1, c2) = cluster
    g = GraphClient(c2, table_id=7, feat_dim=3)
    ids = np.array([100, 200, 300], np.uint64)
    feats = np.arange(9, dtype=np.float32).reshape(3, 3)
    g.set_node_feat(ids, feats)
    np.testing.assert_allclose(g.get_node_feat(ids), feats)
    # unknown id -> zeros
    np.testing.assert_allclose(g.get_node_feat(np.array([999])), 0.0)
    g.add_edges([100], [200], bidirectional=True)
    np.testing.assert_array_equal(g.degree(np.array([100, 200])), [1, 1])


def test_graph_save_load_roundtrip(cluster, tmp_path):
    _, (c1, _) = cluster
    g = GraphClient(c1, table_id=7, feat_dim=3)
    g.add_edges(np.array([77, 77]), np.array([1, 2]))
    g.set_node_feat(np.array([77]), np.array([[9.0, 8.0, 7.0]], np.float32))
    c1.save(str(tmp_path / "ckpt"))

    # fresh servers load the dump
    dense = [DenseTableConfig(table_id=1, dim=6)]
    sparse = [SparseTableConfig(table_id=0, dim=4, initial_range=0.0)]
    graph = [GraphTableConfig(table_id=7, feat_dim=3)]
    servers2 = [PSServer(0, sparse, dense, graph),
                PSServer(0, sparse, dense, graph)]
    c3 = PSClient([f"127.0.0.1:{s.port}" for s in servers2])
    try:
        c3.load(str(tmp_path / "ckpt"))
        g3 = GraphClient(c3, table_id=7, feat_dim=3)
        np.testing.assert_array_equal(g3.degree(np.array([77])), [2])
        np.testing.assert_allclose(g3.get_node_feat(np.array([77])),
                                   [[9.0, 8.0, 7.0]])
    finally:
        c3.close()
        for s in servers2:
            s.stop()

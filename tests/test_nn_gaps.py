"""Tests for the nn/functional gap fill: adaptive pools, max-unpool roundtrip,
losses (CTC cross-checked against torch), grid ops, fold, spectral norm, etc."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestAdaptivePools:
    def test_adaptive_avg_pool3d(self):
        x = np.random.RandomState(0).rand(2, 3, 8, 8, 8).astype(np.float32)
        out = nn.AdaptiveAvgPool3D(2)(t(x))
        assert out.shape == [2, 3, 2, 2, 2]
        np.testing.assert_allclose(
            out.numpy()[0, 0, 0, 0, 0], x[0, 0, :4, :4, :4].mean(), rtol=1e-5)

    def test_adaptive_max_pool1d_3d(self):
        x = np.random.RandomState(0).rand(2, 3, 9).astype(np.float32)
        out = nn.AdaptiveMaxPool1D(3)(t(x))
        assert out.shape == [2, 3, 3]
        np.testing.assert_allclose(out.numpy()[0, 0, 0], x[0, 0, :3].max(), rtol=1e-6)
        x3 = np.random.RandomState(0).rand(2, 3, 4, 4, 4).astype(np.float32)
        out3 = nn.AdaptiveMaxPool3D(2)(t(x3))
        assert out3.shape == [2, 3, 2, 2, 2]

    def test_uneven_adaptive(self):
        x = np.arange(7, dtype=np.float32).reshape(1, 1, 7)
        out = F.adaptive_max_pool1d(t(x), 3)
        # windows: [0:3), [2:5), [4:7) per the floor/ceil rule
        np.testing.assert_allclose(out.numpy()[0, 0], [2, 4, 6])


class TestMaxUnpool:
    def test_pool_unpool_roundtrip_2d(self):
        rs = np.random.RandomState(0)
        x = rs.rand(2, 3, 8, 8).astype(np.float32)
        vals, idx = F.max_pool2d(t(x), 2, 2, return_mask=True)
        assert vals.shape == [2, 3, 4, 4] and idx.shape == [2, 3, 4, 4]
        # indices are flat positions into 8*8; values match gathering by index
        flat = x.reshape(2, 3, 64)
        np.testing.assert_allclose(
            np.take_along_axis(flat, idx.numpy().reshape(2, 3, 16), -1),
            vals.numpy().reshape(2, 3, 16), rtol=1e-6)
        un = F.max_unpool2d(vals, idx, 2, 2)
        assert un.shape == [2, 3, 8, 8]
        # unpooled has the max values at their original places, zeros elsewhere
        assert np.count_nonzero(un.numpy()) == 2 * 3 * 16
        np.testing.assert_allclose(un.numpy().max(axis=(2, 3)),
                                   x.max(axis=(2, 3)), rtol=1e-6)

    def test_unpool_matches_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(1)
        x = rs.rand(1, 2, 6, 6).astype(np.float32)
        vals, idx = F.max_pool2d(t(x), 2, 2, return_mask=True)
        un = F.max_unpool2d(vals, idx, 2, 2)
        tv, ti = torch.nn.functional.max_pool2d(
            torch.tensor(x), 2, 2, return_indices=True)
        tun = torch.nn.functional.max_unpool2d(tv, ti, 2, 2)
        np.testing.assert_allclose(vals.numpy(), tv.numpy(), rtol=1e-6)
        np.testing.assert_array_equal(idx.numpy(), ti.numpy())
        np.testing.assert_allclose(un.numpy(), tun.numpy(), rtol=1e-6)

    def test_unpool_1d_3d_shapes(self):
        x = np.random.RandomState(0).rand(2, 3, 8).astype(np.float32)
        v, i = F.max_pool1d(t(x), 2, return_mask=True)
        assert F.max_unpool1d(v, i, 2).shape == [2, 3, 8]
        x3 = np.random.RandomState(0).rand(1, 2, 4, 4, 4).astype(np.float32)
        v3, i3 = F.max_pool3d(t(x3), 2, return_mask=True)
        assert F.max_unpool3d(v3, i3, 2).shape == [1, 2, 4, 4, 4]

    def test_layers(self):
        x = t(np.random.RandomState(0).rand(1, 1, 4, 4).astype(np.float32))
        v, i = F.max_pool2d(x, 2, 2, return_mask=True)
        assert nn.MaxUnPool2D(2, 2)(v, i).shape == [1, 1, 4, 4]

    def test_grad_through_pool_with_indices(self):
        x = t(np.random.RandomState(0).rand(1, 1, 4, 4).astype(np.float32))
        x.stop_gradient = False
        v, i = F.max_pool2d(x, 2, 2, return_mask=True)
        F.max_unpool2d(v, i, 2, 2).sum().backward()
        # each window's max gets grad 1, others 0
        assert float(x.grad.numpy().sum()) == 4.0


class TestLosses:
    def test_log_loss(self):
        p = np.array([[0.9], [0.1]], np.float32)
        l = np.array([[1.0], [0.0]], np.float32)
        out = F.log_loss(t(p), t(l)).numpy()
        ref = -l * np.log(p + 1e-4) - (1 - l) * np.log(1 - p + 1e-4)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_dice_loss(self):
        # perfect prediction -> loss ~ 0
        lab = np.array([[0], [1]], np.int64)
        pred = np.eye(2, dtype=np.float32)[lab.reshape(-1)]
        out = float(F.dice_loss(t(pred), t(lab)))
        assert out < 1e-4

    def test_hinge_embedding_loss(self):
        x = np.array([1.0, 0.4], np.float32)
        y = np.array([1.0, -1.0], np.float32)
        out = float(F.hinge_embedding_loss(t(x), t(y), margin=1.0))
        np.testing.assert_allclose(out, (1.0 + 0.6) / 2, rtol=1e-6)
        loss_layer = nn.HingeEmbeddingLoss(reduction="sum")
        np.testing.assert_allclose(float(loss_layer(t(x), t(y))), 1.6, rtol=1e-6)

    def test_npair_loss_runs(self):
        rs = np.random.RandomState(0)
        a = rs.rand(4, 8).astype(np.float32)
        p = rs.rand(4, 8).astype(np.float32)
        l = np.array([0, 1, 0, 2], np.int64)
        out = float(F.npair_loss(t(a), t(p), t(l)))
        assert out > 0

    def test_margin_cross_entropy(self):
        rs = np.random.RandomState(0)
        cosv = np.clip(rs.rand(4, 10).astype(np.float32), 0.1, 0.9)
        lab = np.array([1, 2, 3, 4], np.int64)
        loss, soft = F.margin_cross_entropy(t(cosv), t(lab), return_softmax=True,
                                            reduction=None)
        assert loss.shape == [4, 1] and soft.shape == [4, 10]
        # margin makes the target logit harder -> loss above plain CE
        plain = -np.log(np.exp(cosv * 64)[np.arange(4), lab]
                        / np.exp(cosv * 64).sum(-1))
        assert (loss.numpy().reshape(-1) >= plain - 1e-3).all()

    def test_ctc_loss_matches_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(0)
        T, N, C, L = 12, 3, 5, 4
        logits = rs.randn(T, N, C).astype(np.float32)
        labels = rs.randint(1, C, (N, L)).astype(np.int64)
        in_len = np.array([12, 10, 8], np.int64)
        lab_len = np.array([4, 3, 2], np.int64)
        out = F.ctc_loss(t(logits), t(labels), t(in_len), t(lab_len),
                         blank=0, reduction=None)
        tl = torch.nn.functional.ctc_loss(
            torch.log_softmax(torch.tensor(logits), -1), torch.tensor(labels),
            torch.tensor(in_len), torch.tensor(lab_len), blank=0,
            reduction="none")
        np.testing.assert_allclose(out.numpy(), tl.numpy(), rtol=1e-4, atol=1e-5)

    def test_ctc_loss_grad_and_layer(self):
        rs = np.random.RandomState(0)
        logits = t(rs.randn(6, 2, 4).astype(np.float32))
        logits.stop_gradient = False
        loss = nn.CTCLoss()(logits, t(np.array([[1, 2], [2, 3]], np.int64)),
                            t(np.array([6, 6], np.int64)),
                            t(np.array([2, 2], np.int64)))
        loss.backward()
        assert logits.grad is not None
        assert np.isfinite(logits.grad.numpy()).all()

    def test_hsigmoid_loss(self):
        paddle.seed(0)
        m = nn.HSigmoidLoss(8, 6)
        x = t(np.random.RandomState(0).rand(4, 8).astype(np.float32))
        lab = t(np.array([[0], [2], [4], [5]], np.int64))
        loss = m(x, lab)
        assert loss.shape == [] or loss.shape == [1]
        assert float(loss) > 0
        # training decreases it
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=m.parameters())
        first = float(loss)
        for _ in range(20):
            loss = m(x, lab)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.7


class TestSpatialOps:
    def test_affine_grid_identity(self):
        theta = np.array([[[1.0, 0, 0], [0, 1, 0]]], np.float32)
        grid = F.affine_grid(t(theta), [1, 1, 3, 3])
        assert grid.shape == [1, 3, 3, 2]
        np.testing.assert_allclose(grid.numpy()[0, 0, 0], [-1, -1], atol=1e-6)
        np.testing.assert_allclose(grid.numpy()[0, 2, 2], [1, 1], atol=1e-6)

    def test_grid_sample_identity(self):
        x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
        theta = np.array([[[1.0, 0, 0], [0, 1, 0]]], np.float32)
        grid = F.affine_grid(t(theta), [1, 1, 3, 3])
        out = F.grid_sample(t(x), grid)
        np.testing.assert_allclose(out.numpy(), x, atol=1e-5)

    def test_grid_sample_matches_torch(self):
        torch = pytest.importorskip("torch")
        rs = np.random.RandomState(0)
        x = rs.rand(2, 3, 5, 5).astype(np.float32)
        grid = (rs.rand(2, 4, 4, 2).astype(np.float32) - 0.5) * 2.2  # incl. OOB
        out = F.grid_sample(t(x), t(grid), align_corners=True)
        ref = torch.nn.functional.grid_sample(
            torch.tensor(x), torch.tensor(grid), mode="bilinear",
            padding_mode="zeros", align_corners=True)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4, atol=1e-5)

    def test_temporal_shift(self):
        x = np.random.RandomState(0).rand(4, 8, 2, 2).astype(np.float32)  # N*T=4
        out = F.temporal_shift(t(x), seg_num=2, shift_ratio=0.25)
        assert out.shape == [4, 8, 2, 2]
        # first quarter channels shifted left: out[t] = x[t+1]
        np.testing.assert_allclose(out.numpy()[0, :2], x[1, :2], rtol=1e-6)
        np.testing.assert_allclose(out.numpy()[1, :2], 0.0, atol=1e-6)

    def test_fold_unfold_roundtrip(self):
        x = np.random.RandomState(0).rand(1, 2, 4, 4).astype(np.float32)
        cols = F.unfold(t(x), 2, strides=2)
        assert cols.shape == [1, 8, 4]
        back = F.fold(cols, (4, 4), 2, strides=2)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)
        assert nn.Fold((4, 4), 2, strides=2)(cols).shape == [1, 2, 4, 4]

    def test_zeropad2d_bilinear(self):
        x = np.ones((1, 1, 2, 2), np.float32)
        out = F.zeropad2d(t(x), [1, 0, 0, 1])
        assert out.shape == [1, 1, 3, 3]
        assert out.numpy()[0, 0, 0, 0] == 0  # left pad column
        w = np.random.RandomState(0).rand(3, 4, 5).astype(np.float32)
        x1 = np.random.RandomState(1).rand(2, 4).astype(np.float32)
        x2 = np.random.RandomState(2).rand(2, 5).astype(np.float32)
        out = F.bilinear(t(x1), t(x2), t(w))
        ref = np.einsum("ni,kij,nj->nk", x1, w, x2)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


class TestMiscLayers:
    def test_softmax2d(self):
        x = np.random.RandomState(0).rand(2, 3, 4, 4).astype(np.float32)
        out = nn.Softmax2D()(t(x))
        np.testing.assert_allclose(out.numpy().sum(1), np.ones((2, 4, 4)), rtol=1e-5)

    def test_silu_alias(self):
        assert nn.Silu is nn.SiLU

    def test_pairwise_distance(self):
        x = np.array([[0.0, 0.0], [1.0, 1.0]], np.float32)
        y = np.array([[3.0, 4.0], [1.0, 1.0]], np.float32)
        out = nn.PairwiseDistance()(t(x), t(y))
        np.testing.assert_allclose(out.numpy(), [5.0, 2e-6 * 2 ** 0.5], rtol=1e-3,
                                   atol=1e-5)

    def test_spectral_norm(self):
        paddle.seed(0)
        w = np.random.RandomState(0).rand(4, 6).astype(np.float32) + 1.0
        sn = nn.SpectralNorm(w.shape, power_iters=20)
        out = sn(t(w))
        # spectral norm of the output ~ 1
        s = np.linalg.svd(out.numpy(), compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=1e-3)

    def test_inplace_functionals(self):
        x = t(np.array([-1.0, 2.0], np.float32))
        y = F.relu_(x)
        assert y is x
        np.testing.assert_allclose(x.numpy(), [0.0, 2.0])
        z = t(np.array([0.5], np.float32))
        F.tanh_(z)
        np.testing.assert_allclose(z.numpy(), np.tanh(0.5), rtol=1e-6)

    def test_class_center_sample(self):
        lab = t(np.array([1, 5, 9], np.int64))
        remapped, sampled = F.class_center_sample(lab, 20, 6)
        s = sampled.numpy()
        assert len(s) == 6
        assert {1, 5, 9}.issubset(set(s.tolist()))
        # remapped labels index into sampled
        np.testing.assert_array_equal(s[remapped.numpy()], [1, 5, 9])

    def test_sparse_attention_semantics(self):
        # full CSR pattern == dense attention
        b, h, T, d = 1, 1, 4, 8
        rs = np.random.RandomState(0)
        q = rs.rand(b, h, T, d).astype(np.float32)
        k = rs.rand(b, h, T, d).astype(np.float32)
        v = rs.rand(b, h, T, d).astype(np.float32)
        offs = np.broadcast_to(np.arange(0, 4 * (T + 1), 4, dtype=np.int64)[None, None],
                               (b, h, T + 1)).copy()
        cols = np.broadcast_to(np.tile(np.arange(T, dtype=np.int64), T)[None, None],
                               (b, h, T * T)).copy()
        out = F.sparse_attention(t(q), t(k), t(v), t(offs), t(cols))
        att = np.einsum("bhtd,bhsd->bhts", q, k) / np.sqrt(d)
        att = np.exp(att - att.max(-1, keepdims=True))
        att /= att.sum(-1, keepdims=True)
        ref = np.einsum("bhts,bhsd->bhtd", att, v)
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_set_image_backend(self):
        import paddle_tpu.vision as vision

        vision.set_image_backend("cv2")
        assert vision.get_image_backend() == "cv2"
        vision.set_image_backend("pil")
        import pytest as _pytest

        with _pytest.raises(ValueError):
            vision.set_image_backend("nope")


def test_cross_entropy_weighted_soft_labels():
    """Class weights + soft labels (previously an explicit deferral),
    REFERENCE semantics (loss.py:1769): the unweighted per-sample soft loss
    scales by weight_gather = sum_c w_c*label_c; mean divides by
    sum(weight_gather). Checked against a numpy reference, grads flow."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    logits = rng.randn(5, 4).astype("float32")
    soft = rng.rand(5, 4).astype("float32")
    soft /= soft.sum(1, keepdims=True)
    w = np.array([0.5, 1.0, 2.0, 1.5], "float32")

    lp = logits - logits.max(1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(1, keepdims=True))
    unweighted = -(soft * lp).sum(1)
    wg = (w[None, :] * soft).sum(1)
    per = wg * unweighted
    ref_mean = per.sum() / wg.sum()

    out = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(soft),
                          weight=paddle.to_tensor(w), soft_label=True)
    assert float(out.item()) == pytest.approx(float(ref_mean), rel=1e-5)
    out_none = F.cross_entropy(
        paddle.to_tensor(logits), paddle.to_tensor(soft),
        weight=paddle.to_tensor(w), soft_label=True, reduction="none")
    np.testing.assert_allclose(np.asarray(out_none.numpy()).squeeze(), per,
                               rtol=1e-5)

    x = paddle.to_tensor(logits)
    x.stop_gradient = False
    F.cross_entropy(x, paddle.to_tensor(soft), weight=paddle.to_tensor(w),
                    soft_label=True).backward()
    assert float(x.grad.abs().sum().item()) > 0


def test_cross_entropy_weighted_soft_labels_grad_paths():
    """Weighted soft labels keep BOTH input and label differentiable (the
    unweighted soft-label convention), including use_softmax=False."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(1)
    probs = rng.rand(3, 4).astype("float32")
    probs /= probs.sum(1, keepdims=True)
    soft = rng.rand(3, 4).astype("float32")
    soft /= soft.sum(1, keepdims=True)
    w = np.array([1.0, 2.0, 0.5, 1.5], "float32")

    x = paddle.to_tensor(probs)
    x.stop_gradient = False
    lb = paddle.to_tensor(soft)
    lb.stop_gradient = False
    out = F.cross_entropy(x, lb, weight=paddle.to_tensor(w), soft_label=True,
                          use_softmax=False)
    out.backward()
    assert float(x.grad.abs().sum().item()) > 0   # probability-input grads
    assert float(lb.grad.abs().sum().item()) > 0  # label grads


def test_cross_entropy_weight_smoothing_ignores_padding():
    """label_smoothing flips hard labels to soft; with a class weight the
    padding rows (ignore_index) must contribute zero loss AND zero weight
    mass — not an eps/K-uniform contribution."""
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(2)
    logits = rng.randn(6, 4).astype("float32")
    labels = np.array([0, 1, -100, 2, -100, 3], "int64")
    w = np.array([1.0, 2.0, 0.5, 1.5], "float32")

    full = F.cross_entropy(paddle.to_tensor(logits),
                           paddle.to_tensor(labels),
                           weight=paddle.to_tensor(w), label_smoothing=0.1)
    # the same batch with padding rows REMOVED must give the same mean
    keep = labels != -100
    sub = F.cross_entropy(paddle.to_tensor(logits[keep]),
                          paddle.to_tensor(labels[keep]),
                          weight=paddle.to_tensor(w), label_smoothing=0.1)
    assert float(full.item()) == pytest.approx(float(sub.item()), rel=1e-5)


def test_cross_entropy_smoothing_padding_unweighted_and_edge_shapes():
    """label_smoothing must exclude padding rows from the mean with or
    without a class weight; (N, 1) hard labels squeeze before one_hot; a
    fully-padded batch returns 0, never 0/0 NaN."""
    import paddle_tpu.nn.functional as F

    logits = np.random.RandomState(0).randn(5, 4).astype("float32")
    labels = np.array([0, -100, 2, -100, 3], "int64")
    full = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels),
                           label_smoothing=0.1)
    keep = labels != -100
    sub = F.cross_entropy(paddle.to_tensor(logits[keep]),
                          paddle.to_tensor(labels[keep]),
                          label_smoothing=0.1)
    assert float(full.item()) == pytest.approx(float(sub.item()), rel=1e-6)

    n1 = F.cross_entropy(paddle.to_tensor(logits),
                         paddle.to_tensor(labels.reshape(-1, 1)),
                         label_smoothing=0.1)
    assert float(n1.item()) == pytest.approx(float(full.item()), rel=1e-6)

    allpad = F.cross_entropy(
        paddle.to_tensor(logits),
        paddle.to_tensor(np.full(5, -100, "int64")),
        weight=paddle.to_tensor(np.ones(4, "float32")), label_smoothing=0.1)
    assert np.isfinite(float(allpad.item()))
    assert float(allpad.item()) == 0.0

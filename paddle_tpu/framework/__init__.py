from . import io  # noqa: F401
from ..core.random import seed  # noqa: F401
from ..core.tensor import Tensor  # noqa: F401

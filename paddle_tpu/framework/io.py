"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:568,784 — pickled nested containers with a tensor
protocol. Same contract here: nested dict/list/tuple of Tensors & ndarrays, tensors serialized
as numpy. Distributed/sharded checkpointing (orbax-style, per-host shards) lives in
distributed/checkpoint.py.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

_PROTO = 4


class _TensorPayload:
    """Pickle-stable tensor wrapper (dtype string survives bfloat16)."""

    def __init__(self, array):
        self.dtype = str(array.dtype)
        if array.dtype.name == "bfloat16":
            self.data = np.asarray(array).astype(np.float32)
            self.bf16 = True
        else:
            self.data = np.asarray(array)
            self.bf16 = False

    def to_array(self):
        if self.bf16:
            from ..core import dtype as dtypes

            return self.data.astype(dtypes.bfloat16)
        return self.data


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    import jax.numpy as jnp

    if isinstance(obj, _TensorPayload):
        arr = obj.to_array()
        return arr if return_numpy else Tensor(jnp.asarray(arr))
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)

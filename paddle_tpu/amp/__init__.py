"""AMP: auto_cast + GradScaler.

Reference: python/paddle/amp/auto_cast.py + grad_scaler.py:26; C++ autocast
imperative/amp_auto_cast.cc; check_finite_and_unscale + update_loss_scaling ops.

TPU-native: the low dtype is bfloat16 whose exponent range equals f32 — loss scaling is
mathematically unnecessary for bf16, so GradScaler becomes a near-no-op there but keeps the full
dynamic-loss-scaling machinery for float16 parity (and for tests).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.autograd import no_grad
from ..core.dispatch import amp_guard
from ..core.tensor import Tensor


def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16"):
    return amp_guard(enable=enable, dtype=dtype, level=level,
                     custom_white_list=custom_white_list,
                     custom_black_list=custom_black_list)


amp_guard = amp_guard  # paddle.fluid.dygraph.amp_guard alias


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None):
    """O2: cast model params to the low dtype (master weights live in the optimizer's
    f32 state, see optimizer/functional.py)."""
    if level == "O2":
        items = models if isinstance(models, (list, tuple)) else [models]
        for m in items:
            for p in m.parameters():
                if dtypes.is_floating(p.dtype):
                    p._data = p._data.astype(dtypes.convert_dtype(dtype))
    if optimizers is None:
        return models
    return models, optimizers


def amp_guard_from_configs(cfg, force_bf16=False):
    """Build the autocast context from a strategy AMPConfig — the single
    mapping used by both the eager meta-optimizer and the traced engine step."""
    from ..core.dispatch import amp_guard

    dtype = getattr(cfg, "dtype", "bfloat16")
    if force_bf16 and dtype == "float16":
        dtype = "bfloat16"
    return amp_guard(
        dtype=dtype,
        level="O2" if getattr(cfg, "use_pure_fp16", False) else "O1",
        custom_white_list=getattr(cfg, "custom_white_list", None),
        custom_black_list=getattr(cfg, "custom_black_list", None))


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    @no_grad()
    def _unscale(self, optimizer):
        """check_finite_and_unscale analogue: one fused finite-check over all grads."""
        if not self._enable:
            return
        found = jnp.zeros((), jnp.bool_)
        inv = 1.0 / self._scale
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data
            found = found | ~jnp.all(jnp.isfinite(g))
            p.grad = Tensor((g * inv).astype(g.dtype))
        self._found_inf = bool(found)
        self._unscaled = True

    def unscale_(self, optimizer):
        self._unscale(optimizer)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self._unscale(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled = False

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._scale = d["scale"]
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)

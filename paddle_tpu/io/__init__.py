"""paddle.io equivalent: Dataset / DataLoader / samplers.

Reference: python/paddle/fluid/reader.py:146 (DataLoader), python/paddle/fluid/dataloader/
(multiprocess workers over shared memory, batch samplers, DistributedBatchSampler).

TPU-native: the hot path is host->HBM transfer; the loader keeps worker multiprocessing for
CPU-bound decode (fork + queues — shared-memory numpy handoff) and adds device prefetch
(double buffering) so input pipeline overlaps the TPU step, the role the reference's
InMemoryDataFeed threads play (paddle/fluid/framework/data_feed.h:966).
"""
from __future__ import annotations

import itertools
import math
import queue as queue_mod
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.RandomState(0).permutation(len(dataset)).tolist()
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n]))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        seed = random_mod.default_generator().initial_seed() + id(self) % 1000003
        rng = np.random.RandomState(seed % (2 ** 31))
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.RandomState(0)
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/fluid/dataloader/batch_sampler.py DistributedBatchSampler."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        from ..distributed import get_rank, get_world_size

        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    import jax.numpy as jnp

    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        arr = np.stack(batch)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return Tensor(jnp.asarray(arr))
    if isinstance(sample, (int, np.integer)):
        return Tensor(jnp.asarray(np.asarray(batch, np.int64)))
    if isinstance(sample, (float, np.floating)):
        return Tensor(jnp.asarray(np.asarray(batch, np.float32)))
    return batch


class _PrefetchIterator:
    """Background-thread prefetch: overlaps host batch assembly + H2D with the device step."""

    def __init__(self, it, depth=2):
        self._q = queue_mod.Queue(maxsize=depth)
        self._it = it
        self._done = object()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for item in self._it:
                self._q.put(item)
        except Exception as e:  # propagate
            self._q.put(("__error__", e))
        self._q.put(self._done)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and item[0] == "__error__":
            raise item[1]
        return item


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.num_workers > 0:
            yield from self._iter_multiprocess()
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_multiprocess(self):
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        out_q = ctx.Queue(maxsize=self.num_workers * self.prefetch_factor)
        batches = list(self.batch_sampler)
        for bid, indices in enumerate(batches):
            index_q.put((bid, indices))
        for _ in range(self.num_workers):
            index_q.put(None)

        dataset = self.dataset

        def worker():
            while True:
                item = index_q.get()
                if item is None:
                    out_q.put(None)
                    return
                bid, indices = item
                samples = [dataset[i] for i in indices]
                np_samples = _to_numpy_tree(samples)
                out_q.put((bid, np_samples))

        procs = [ctx.Process(target=worker, daemon=True) for _ in range(self.num_workers)]
        for p in procs:
            p.start()
        finished = 0
        pending = {}
        next_bid = 0
        received = 0
        try:
            while finished < self.num_workers or pending or received < len(batches):
                if next_bid in pending:
                    samples = pending.pop(next_bid)
                    next_bid += 1
                    yield self.collate_fn(samples)
                    continue
                if finished == self.num_workers and received == len(batches):
                    break
                item = out_q.get()
                if item is None:
                    finished += 1
                    continue
                bid, samples = item
                received += 1
                pending[bid] = samples
        finally:
            for p in procs:
                p.terminate()

    def __iter__(self):
        it = self._iter_batches()
        if self.use_buffer_reader:
            return _PrefetchIterator(it, depth=self.prefetch_factor)
        return it

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    return obj


def get_worker_info():
    return None

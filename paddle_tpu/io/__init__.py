"""paddle.io equivalent: Dataset / DataLoader / samplers.

Reference: python/paddle/fluid/reader.py:146 (DataLoader), python/paddle/fluid/dataloader/
(multiprocess workers over shared memory, batch samplers, DistributedBatchSampler).

TPU-native: the hot path is host->HBM transfer; with num_workers > 0 a thread
pool runs dataset fetch + collate ahead of the consumer into a bounded queue
(collate releases the GIL in jnp's C layer, and the produced batches are
device-ready arrays, so no pickling/shared-memory handoff is needed), the role
the reference's InMemoryDataFeed threads play (paddle/fluid/framework/
data_feed.h:966). The engine-side half of the pipeline —
distributed.DevicePrefetcher / TrainStepEngine.prefetch — then issues the
sharded device_put for the next batches while the current step executes.
"""
from __future__ import annotations

import itertools
import math
import queue as queue_mod
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.RandomState(0).permutation(len(dataset)).tolist()
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n]))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self.num_samples = num_samples or len(data_source)

    def __iter__(self):
        n = len(self.data_source)
        seed = random_mod.default_generator().initial_seed() + id(self) % 1000003
        rng = np.random.RandomState(seed % (2 ** 31))
        if self.replacement:
            return iter(rng.randint(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.RandomState(0)
        return iter(rng.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/fluid/dataloader/batch_sampler.py DistributedBatchSampler."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle
        from ..distributed import get_rank, get_world_size

        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = list(range(n))
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    import jax.numpy as jnp

    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn([b[i] for b in batch]) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return Tensor(jnp.stack([s._data for s in batch]))
    if isinstance(sample, np.ndarray):
        arr = np.stack(batch)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return Tensor(jnp.asarray(arr))
    if isinstance(sample, (int, np.integer)):
        return Tensor(jnp.asarray(np.asarray(batch, np.int64)))
    if isinstance(sample, (float, np.floating)):
        return Tensor(jnp.asarray(np.asarray(batch, np.float32)))
    return batch


class _PrefetchIterator:
    """Background-thread prefetch: overlaps host batch assembly + H2D with the device step.

    Single producer thread filling a bounded queue; the consumer pays only
    residual (non-overlapped) wait. Producer exceptions are re-raised at the
    consumer's next(); close() (also on GC) stops the producer promptly even
    when the consumer abandons the iterator mid-epoch — without it the
    producer would block forever on a full queue."""

    _DONE = object()

    def __init__(self, it, depth=2):
        self._q = queue_mod.Queue(maxsize=max(1, depth))
        self._it = it
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for item in self._it:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # propagate to the consumer
            if not self._stop.is_set():
                self._q.put(("__error__", e))
            return
        self._q.put(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._stop.set()
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 and item[0] == "__error__":
            self.close()
            raise item[1]
        return item

    def close(self):
        self._stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=1.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _WorkerError:
    """Carrier re-raising a worker exception at the consumer, batch-ordered."""

    def __init__(self, exc):
        self.exc = exc


class _OrderedWorkerPool:
    """num_workers threads run dataset fetch + collate AHEAD of the consumer.

    The producer side of the async input pipeline: each worker pulls a
    (batch_id, indices) task, materializes samples and collates them into
    device-ready arrays, and pushes into a bounded output queue
    (num_workers * prefetch_factor deep — total look-ahead is bounded, like
    the reference's multiprocess DataLoader outstanding-batch cap). The
    consumer reorders by batch_id so delivery order matches the sampler
    regardless of worker scheduling. Shutdown is cooperative: close() (also
    via GC / generator close) sets a stop event that both the task pull and
    the output put observe, then joins the threads."""

    def __init__(self, dataset, batches, collate_fn, num_workers,
                 prefetch_factor):
        self._dataset = dataset
        self._collate_fn = collate_fn
        self._n_batches = len(batches)
        self._task_q = queue_mod.Queue()
        for task in enumerate(batches):
            self._task_q.put(task)
        self._out_q = queue_mod.Queue(
            maxsize=max(1, num_workers * max(1, prefetch_factor)))
        self._stop = threading.Event()
        self._pending = {}
        self._next_bid = 0
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"paddle_tpu-io-worker-{i}")
            for i in range(max(1, num_workers))]
        for t in self._threads:
            t.start()

    def _worker(self):
        while not self._stop.is_set():
            try:
                bid, indices = self._task_q.get_nowait()
            except queue_mod.Empty:
                return
            try:
                item = self._collate_fn([self._dataset[i] for i in indices])
            except BaseException as e:
                item = _WorkerError(e)
            while not self._stop.is_set():
                try:
                    self._out_q.put((bid, item), timeout=0.1)
                    break
                except queue_mod.Full:
                    continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set() or self._next_bid >= self._n_batches:
            self.close()
            raise StopIteration
        # every task yields exactly one queue item, so this get terminates;
        # task pickup is FIFO, so next_bid is always among the in-flight set
        while self._next_bid not in self._pending:
            bid, item = self._out_q.get()
            self._pending[bid] = item
        item = self._pending.pop(self._next_bid)
        self._next_bid += 1
        if isinstance(item, _WorkerError):
            self.close()
            raise item.exc
        return item

    def close(self):
        self._stop.set()
        while True:  # unblock workers stuck on a full output queue
            try:
                self._out_q.get_nowait()
            except queue_mod.Empty:
                break
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=1.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.use_buffer_reader = use_buffer_reader
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        # num_workers > 0: a thread pool runs fetch + collate ahead of the
        # consumer into a bounded queue (batch order preserved; exceptions
        # re-raised at next(); clean shutdown on close/GC). Iterable datasets
        # cannot be index-partitioned, so they keep a single producer thread.
        if self.num_workers > 0 and not self._iterable_mode:
            return _OrderedWorkerPool(
                self.dataset, list(self.batch_sampler), self.collate_fn,
                self.num_workers, self.prefetch_factor)
        it = self._iter_batches()
        if self.num_workers > 0 or self.use_buffer_reader:
            return _PrefetchIterator(it, depth=self.prefetch_factor)
        return it

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)


def get_worker_info():
    return None

"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's capabilities.

Built from scratch for JAX/XLA/Pallas/pjit — not a port. See SURVEY.md at the repo root for the
reference blueprint this build follows; reference file:line citations appear in module docstrings.
"""
from __future__ import annotations

from .version import full_version as __version__

# int64 is paddle's default integer dtype; jax demotes to 32-bit unless x64 is on.
# Float defaults remain f32 because every creation path passes dtype explicitly
# (python float scalars stay weakly typed, so f64 does not leak into f32 compute).
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# ---- core ----
from .core import dtype as _dtype_mod
from .core.dtype import (
    bfloat16, bool_, complex64, complex128, convert_dtype, finfo, float16,
    float32, float64, get_default_dtype, iinfo, int8, int16, int32, int64,
    set_default_dtype, uint8,
)
from .core.place import (
    CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, IPUPlace, MLUPlace,
    NPUPlace, NPUPinnedPlace, Place, TPUPlace, XPUPlace, device_count,
    get_device, is_compiled_with_cinn, is_compiled_with_cuda,
    is_compiled_with_distribute, is_compiled_with_ipu, is_compiled_with_mlu,
    is_compiled_with_npu, is_compiled_with_rocm, is_compiled_with_tpu,
    is_compiled_with_xpu, set_device,
)
from .core.random import get_rng_state, seed, set_rng_state

# the reference's CUDA RNG state API maps onto the single device RNG here
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state
from .core.flags import get_flags, set_flags
from .core import compile_cache as _compile_cache  # noqa: F401  (applies
#   FLAGS_compile_cache_dir / PADDLE_TPU_COMPILE_CACHE at import)
from .core.tensor import Tensor
from .core.autograd import enable_grad, grad, is_grad_enabled, no_grad, set_grad_enabled
from .core.dispatch import amp_guard as _amp_guard  # noqa: F401

# ---- ops (also attaches Tensor methods) ----
from .ops import *  # noqa: F401,F403
from .ops import F as _F  # noqa: F401

bool = bool_  # paddle.bool

# ---- subpackages ----
from . import nn  # noqa: E402
from . import optimizer  # noqa: E402
from . import autograd  # noqa: E402
from . import amp  # noqa: E402
from . import io  # noqa: E402
from . import vision  # noqa: E402
from . import distributed  # noqa: E402
from . import jit  # noqa: E402
from . import static  # noqa: E402
from . import inference  # noqa: E402
from . import serving  # noqa: E402
from . import fft  # noqa: E402
from .ops import linalg as linalg  # noqa: E402
import sys as _sys
_sys.modules[__name__ + ".linalg"] = linalg  # importable paddle_tpu.linalg, like paddle.linalg
del _sys
from . import distribution  # noqa: E402
from . import sparse  # noqa: E402
from . import strings  # noqa: E402
from . import text  # noqa: E402
from . import incubate  # noqa: E402
from . import metric  # noqa: E402
from . import observability  # noqa: E402
from . import profiler  # noqa: E402
from . import device  # noqa: E402
from . import utils  # noqa: E402
from . import regularizer  # noqa: E402
from . import signal  # noqa: E402
from . import callbacks  # noqa: E402
from . import hub  # noqa: E402
from . import sysconfig  # noqa: E402
from . import reader  # noqa: E402
from . import onnx  # noqa: E402
from . import compat  # noqa: E402
from . import cost_model  # noqa: E402
from . import hapi  # noqa: E402
from .hapi import Model  # noqa: E402
from .framework import io as _fw_io  # noqa: E402
from .framework.io import load, save  # noqa: E402
from .jit import to_static  # noqa: E402

# paddle.disable_static / enable_static parity: dygraph is the default mode.
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static(place=None):
    global _static_mode
    _static_mode = False
    if place is not None:
        set_device(place)


def in_dynamic_mode():
    return not _static_mode


def is_grad_enabled_():  # legacy alias
    return is_grad_enabled()


def summary(net, input_size=None, dtypes=None, input=None):
    from .hapi.summary import summary as _summary

    return _summary(net, input_size, dtypes=dtypes, input=input)


def flops(net, input_size=None, inputs=None, custom_ops=None, print_detail=False):
    from .hapi.dynamic_flops import flops as _flops

    return _flops(net, input_size, inputs=inputs, custom_ops=custom_ops,
                  print_detail=print_detail)


# ---- remaining top-level parity surface ----
from .nn.layer import ParamAttr, create_parameter  # noqa: E402
from .distributed.meta_parallel.data_parallel import DataParallel  # noqa: E402

import numpy as _np  # noqa: E402

dtype = _np.dtype  # paddle.dtype: dtypes here ARE numpy dtypes (see core/dtype.py)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    from .core import tensor as _tensor_mod

    opts = _tensor_mod._print_options
    if precision is not None:
        opts["precision"] = precision
    if threshold is not None:
        opts["threshold"] = threshold
    if edgeitems is not None:
        opts["edgeitems"] = edgeitems
    if linewidth is not None:
        opts["max_line_width"] = linewidth
    if sci_mode is not None:
        opts["suppress_small"] = not sci_mode


def disable_signal_handler():
    """No-op: unlike the reference (platform/init.cc SignalHandle) no custom
    signal handlers are installed, so there is nothing to disable."""


def batch(reader, batch_size, drop_last=False):
    """Wrap a sample reader into a batch reader (reference: python/paddle/batch.py)."""

    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def tolist(x):
    return x.tolist()


def tanh_(x):
    return x.tanh_()


def squeeze_(x, axis=None, name=None):
    return x.squeeze_(axis)


def unsqueeze_(x, axis, name=None):
    return x.unsqueeze_(axis)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x.scatter_(index, updates, overwrite)

"""Self-contained ONNX protobuf writer (no `onnx` package needed).

ONNX models are protobuf messages; this module hand-encodes the wire format
(varint / length-delimited fields) for the subset of onnx.proto3 the exporter
emits: ModelProto, GraphProto, NodeProto, TensorProto, ValueInfoProto,
AttributeProto. Field numbers follow the stable onnx.proto3 schema
(github.com/onnx/onnx/blob/main/onnx/onnx.proto3); tests decode the bytes back
with an independent reader and execute the graph against eager outputs.

Reference parity: python/paddle/onnx/export.py (which shells out to
paddle2onnx); here the emission is native.
"""
from __future__ import annotations

import struct

import numpy as np

# ---- wire primitives --------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:
        n &= (1 << 64) - 1  # protobuf int64 negative: 10-byte twos-complement
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def field_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def field_string(field: int, value: str) -> bytes:
    return field_bytes(field, value.encode("utf-8"))


def field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def field_packed_int64(field: int, values) -> bytes:
    payload = b"".join(_varint(int(v)) for v in values)
    return field_bytes(field, payload)


# ---- onnx messages ----------------------------------------------------------

# TensorProto.DataType
DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6, "int64": 7,
         "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}

# AttributeProto.AttributeType
_ATTR_FLOAT, _ATTR_INT, _ATTR_STRING = 1, 2, 3
_ATTR_TENSOR, _ATTR_FLOATS, _ATTR_INTS = 4, 6, 7


def tensor(name: str, array: np.ndarray) -> bytes:
    """TensorProto with raw_data payload."""
    array = np.ascontiguousarray(array)
    dt = DTYPE[str(array.dtype)]
    msg = b"".join(field_varint(1, d) for d in array.shape)
    msg += field_varint(2, dt)
    msg += field_string(8, name)
    msg += field_bytes(9, array.tobytes())  # raw_data: little-endian
    return msg


def attribute(name: str, value) -> bytes:
    msg = field_string(1, name)
    if isinstance(value, float):
        msg += field_float(2, value) + field_varint(20, _ATTR_FLOAT)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        msg += field_varint(3, int(value)) + field_varint(20, _ATTR_INT)
    elif isinstance(value, str):
        msg += field_string(4, value) + field_varint(20, _ATTR_STRING)
    elif isinstance(value, np.ndarray):
        msg += field_bytes(5, tensor(name + "_value", value))
        msg += field_varint(20, _ATTR_TENSOR)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], float):
        msg += field_bytes(7, b"".join(struct.pack("<f", v) for v in value))
        msg += field_varint(20, _ATTR_FLOATS)
    elif isinstance(value, (list, tuple)):
        msg += field_packed_int64(8, value) + field_varint(20, _ATTR_INTS)
    else:
        raise TypeError(f"onnx attribute {name}: {type(value)}")
    return msg


def node(op_type: str, inputs, outputs, name: str = "", **attrs) -> bytes:
    msg = b"".join(field_string(1, i) for i in inputs)
    msg += b"".join(field_string(2, o) for o in outputs)
    if name:
        msg += field_string(3, name)
    msg += field_string(4, op_type)
    for k, v in attrs.items():
        msg += field_bytes(5, attribute(k, v))
    return msg


def value_info(name: str, dtype: str, shape) -> bytes:
    shape_msg = b"".join(
        field_bytes(1, field_varint(1, int(d)) if isinstance(d, (int, np.integer))
                    else field_string(2, str(d)))
        for d in shape)
    tensor_type = field_varint(1, DTYPE[dtype]) + field_bytes(2, shape_msg)
    type_proto = field_bytes(1, tensor_type)
    return field_string(1, name) + field_bytes(2, type_proto)


def graph(name: str, nodes, inputs, outputs, initializers) -> bytes:
    msg = b"".join(field_bytes(1, n) for n in nodes)
    msg += field_string(2, name)
    msg += b"".join(field_bytes(5, t) for t in initializers)
    msg += b"".join(field_bytes(11, vi) for vi in inputs)
    msg += b"".join(field_bytes(12, vi) for vi in outputs)
    return msg


def model(graph_msg: bytes, opset_version: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    opset = field_string(1, "") + field_varint(2, opset_version)
    msg = field_varint(1, 8)  # ir_version 8
    msg += field_string(2, producer)
    msg += field_bytes(7, graph_msg)
    msg += field_bytes(8, opset)
    return msg

"""jaxpr -> ONNX graph emission.

The exporter traces the Layer's forward (params as explicit inputs, so they
become named initializers) to a ClosedJaxpr, then maps each equation's
primitive onto ONNX ops. Anything outside the supported set raises a clear
NotImplementedError naming the primitive — no silent mis-translation.

Reference parity: python/paddle/onnx/export.py (paddle2onnx's op mappers);
here the source of truth is the traced jaxpr, so every nn.Layer whose forward
lowers to the supported primitive set exports, not a hand-enumerated layer
list.
"""
from __future__ import annotations

import numpy as np

from . import _proto as P

_UNARY = {"neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
          "logistic": "Sigmoid", "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign",
          "floor": "Floor", "ceil": "Ceil", "round": "Round", "erf": "Erf",
          "sin": "Sin", "cos": "Cos"}
_BINARY = {"add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
           "max": "Max", "min": "Min", "pow": "Pow",
           "eq": "Equal", "lt": "Less", "le": "LessOrEqual",
           "gt": "Greater", "ge": "GreaterOrEqual",
           "and": "And", "or": "Or", "xor": "Xor"}

_JAX2ONNX_DTYPE = {"float32": "float32", "float64": "float64",
                   "int32": "int32", "int64": "int64", "bool": "bool",
                   "float16": "float16", "bfloat16": "bfloat16",
                   "uint8": "uint8", "int8": "int8"}


class _Graph:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.names = {}     # jaxpr Var -> onnx value name
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, var):
        from jax._src.core import Literal

        if isinstance(var, Literal):
            return self.const(np.asarray(var.val))
        if var not in self.names:
            self.names[var] = self.fresh("v")
        return self.names[var]

    def const(self, array, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(P.tensor(name, np.ascontiguousarray(array)))
        return name

    def emit(self, op, inputs, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(P.node(op, inputs, outs, **attrs))
        return outs if n_out > 1 else outs[0]

    def alias(self, var, name):
        self.names[var] = name


def _dtype_of(aval) -> str:
    return _JAX2ONNX_DTYPE[str(aval.dtype)]


def _emit_eqn(g: _Graph, eqn):
    prim = eqn.primitive.name
    ins = [g.name_of(v) for v in eqn.invars]
    params = eqn.params

    def out(name):
        g.alias(eqn.outvars[0], name)

    if prim in _UNARY:
        out(g.emit(_UNARY[prim], [ins[0]]))
    elif prim in _BINARY:
        out(g.emit(_BINARY[prim], ins))
    elif prim == "rsqrt":
        out(g.emit("Reciprocal", [g.emit("Sqrt", [ins[0]])]))
    elif prim == "square":
        out(g.emit("Mul", [ins[0], ins[0]]))
    elif prim == "is_finite":
        # finite = not (isinf or isnan); IsInf alone has wrong NaN semantics
        isinf = g.emit("IsInf", [ins[0]])
        isnan = g.emit("IsNaN", [ins[0]])
        out(g.emit("Not", [g.emit("Or", [isinf, isnan])]))
    elif prim == "ne":
        out(g.emit("Not", [g.emit("Equal", ins)]))
    elif prim == "not":
        out(g.emit("Not", [ins[0]]))
    elif prim == "rem":
        # lax.rem is C-style truncated remainder (sign of the dividend):
        # ONNX Mod needs fmod=1 (fmod=0 is divisor-signed and integer-only)
        out(g.emit("Mod", ins, fmod=1))
    elif prim == "integer_pow":
        y = g.const(np.asarray(params["y"],
                               str(eqn.invars[0].aval.dtype)), "exponent")
        out(g.emit("Pow", [ins[0], y]))
    elif prim == "stop_gradient" or prim == "copy":
        out(g.emit("Identity", [ins[0]]))
    elif prim == "convert_element_type":
        to = P.DTYPE[_JAX2ONNX_DTYPE[str(params["new_dtype"])]]
        out(g.emit("Cast", [ins[0]], to=to))
    elif prim == "select_n":
        if len(eqn.invars) == 3:
            # select_n(pred, on_false, on_true) -> Where(pred, on_true, on_false)
            out(g.emit("Where", [ins[0], ins[2], ins[1]]))
        else:
            # integer selector with N cases: cascade Where(pred == k, case_k)
            # over k = 1..N-1 starting from case_0 (out-of-range selectors are
            # clamped by lax; the cascade's fall-through to case_0 differs
            # only on inputs lax already deems undefined)
            pdt = str(eqn.invars[0].aval.dtype)
            acc = ins[1]
            for k in range(2, len(eqn.invars)):
                kk = g.const(np.asarray(k - 1, pdt), "case_idx")
                acc = g.emit("Where", [g.emit("Equal", [ins[0], kk]),
                                       ins[k], acc])
            out(acc)
    elif prim == "reshape":
        shape = g.const(np.asarray(params["new_sizes"], np.int64), "shape")
        out(g.emit("Reshape", [ins[0], shape]))
    elif prim == "squeeze":
        axes = g.const(np.asarray(params["dimensions"], np.int64), "axes")
        out(g.emit("Squeeze", [ins[0], axes]))
    elif prim == "transpose":
        out(g.emit("Transpose", [ins[0]],
                   perm=[int(p) for p in params["permutation"]]))
    elif prim == "broadcast_in_dim":
        shape, bdims = params["shape"], params["broadcast_dimensions"]
        # insert singleton axes at the target rank, then Expand
        inter = [1] * len(shape)
        for src, dst in enumerate(bdims):
            inter[dst] = eqn.invars[0].aval.shape[src]
        rs = g.const(np.asarray(inter, np.int64), "shape")
        mid = g.emit("Reshape", [ins[0], rs])
        ex = g.const(np.asarray(shape, np.int64), "shape")
        out(g.emit("Expand", [mid, ex]))
    elif prim == "concatenate":
        out(g.emit("Concat", ins, axis=int(params["dimension"])))
    elif prim == "iota":
        # static shapes at export: bake the index ramp as an initializer
        shape = [int(s) for s in params["shape"]]
        dim = int(params["dimension"])
        ramp = np.arange(shape[dim], dtype=str(params["dtype"]))
        bshape = [1] * len(shape)
        bshape[dim] = shape[dim]
        out(g.const(np.broadcast_to(ramp.reshape(bshape), shape), "iota"))
    elif prim == "rev":
        # lax.rev (kernel flip in transposed conv) -> Slice with step -1
        dims = [int(d) for d in params["dimensions"]]
        shape = eqn.invars[0].aval.shape
        args = [ins[0]] + [g.const(np.asarray(a, np.int64), h) for a, h in [
            ([-1] * len(dims), "starts"),
            ([-(int(shape[d]) + 1) for d in dims], "ends"),
            (dims, "axes"),
            ([-1] * len(dims), "steps")]]
        out(g.emit("Slice", args))
    elif prim == "slice":
        starts, limits = params["start_indices"], params["limit_indices"]
        strides = params["strides"] or [1] * len(starts)
        axes = list(range(len(starts)))
        args = [ins[0]] + [g.const(np.asarray(a, np.int64), h) for a, h in
                           [(starts, "starts"), (limits, "ends"),
                            (axes, "axes"), (strides, "steps")]]
        out(g.emit("Slice", args))
    elif prim == "reduce_sum":
        axes = g.const(np.asarray(params["axes"], np.int64), "axes")
        out(g.emit("ReduceSum", [ins[0], axes], keepdims=0))
    elif prim in ("reduce_max", "reduce_min"):
        op = "ReduceMax" if prim == "reduce_max" else "ReduceMin"
        out(g.emit(op, [ins[0]], axes=[int(a) for a in params["axes"]],
                   keepdims=0))
    elif prim in ("argmax", "argmin"):
        onnx_op = "ArgMax" if prim == "argmax" else "ArgMin"
        axes = [int(a) for a in params["axes"]]
        src = ins[0]
        if len(axes) == 1:
            am = g.emit(onnx_op, [src], axis=axes[0], keepdims=0)
        else:
            # multi-axis: transpose the reduced axes (in order) to the back,
            # flatten them into one, then a single trailing ArgMax — the
            # index is into the row-major flattening of those axes, matching
            # lax's multi-axis semantics
            shape = [int(s) for s in eqn.invars[0].aval.shape]
            keep = [d for d in range(len(shape)) if d not in axes]
            perm = keep + axes
            if perm != list(range(len(shape))):
                src = g.emit("Transpose", [src], perm=perm)
            flat = [shape[d] for d in keep] + \
                [int(np.prod([shape[d] for d in axes]))]
            src = g.emit("Reshape", [src, g.const(
                np.asarray(flat, np.int64), "shape")])
            am = g.emit(onnx_op, [src], axis=len(flat) - 1, keepdims=0)
        to = P.DTYPE[_JAX2ONNX_DTYPE[str(eqn.outvars[0].aval.dtype)]]
        out(g.emit("Cast", [am], to=to))
    elif prim == "dot_general":
        out(_emit_dot_general(g, eqn, ins))
    elif prim == "conv_general_dilated":
        out(_emit_conv(g, eqn, ins))
    elif prim == "reduce_window_max":
        out(_emit_pool(g, eqn, ins, "MaxPool"))
    elif prim == "reduce_window_sum":
        out(_emit_pool(g, eqn, ins, "SumPool"))
    elif prim in ("jit", "pjit", "closed_call", "custom_jvp_call",
                  "custom_vjp_call", "remat", "checkpoint",
                  "custom_jvp_call_jaxpr"):
        inner = params.get("jaxpr") or params.get("call_jaxpr") \
            or params.get("fun_jaxpr")
        if inner is None:
            raise NotImplementedError(f"onnx export: {prim} without jaxpr")
        _inline(g, inner, eqn.invars, eqn.outvars)
    else:
        raise NotImplementedError(
            f"onnx export: primitive {prim!r} has no ONNX mapping yet; "
            f"use paddle.jit.save (StableHLO) for full-fidelity export")


def _emit_dot_general(g, eqn, ins):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    lname, rname = ins
    # fast path: numpy-matmul-shaped contractions emit one MatMul
    if (len(lc) == 1 and len(rc) == 1
            and tuple(lc) == (lhs.ndim - 1,)
            and tuple(lb) == tuple(range(len(lb)))
            and tuple(rb) == tuple(range(len(rb)))):
        expected_rc = 0 if rhs.ndim == 2 else rhs.ndim - 2
        if rc[0] == expected_rc:
            return g.emit("MatMul", [lname, rname])
        if rhs.ndim == 2:  # weight stored [out, in]: transpose once
            rname = g.emit("Transpose", [rname], perm=[1, 0])
            return g.emit("MatMul", [lname, rname])
    # general case: canonicalize to ONE batched MatMul —
    # transpose to (batch, free, contract) x (batch, contract, free),
    # flatten each group, contract, reshape to jax's output layout
    # (batch..., lhs_free..., rhs_free...)
    l_free = [d for d in range(lhs.ndim) if d not in lb and d not in lc]
    r_free = [d for d in range(rhs.ndim) if d not in rb and d not in rc]
    bshape = [int(lhs.shape[d]) for d in lb]
    mshape = [int(lhs.shape[d]) for d in l_free]
    kshape = [int(lhs.shape[d]) for d in lc]
    nshape = [int(rhs.shape[d]) for d in r_free]
    B = int(np.prod(bshape)) if bshape else 1
    M, K, N = (int(np.prod(s)) if s else 1 for s in (mshape, kshape, nshape))

    lt = g.emit("Transpose", [lname], perm=[int(d) for d in
                                            (*lb, *l_free, *lc)])
    rt = g.emit("Transpose", [rname], perm=[int(d) for d in
                                            (*rb, *rc, *r_free)])
    l2 = g.emit("Reshape", [lt, g.const(np.asarray([B, M, K], np.int64),
                                        "shape")])
    r2 = g.emit("Reshape", [rt, g.const(np.asarray([B, K, N], np.int64),
                                        "shape")])
    mm = g.emit("MatMul", [l2, r2])
    out_shape = bshape + mshape + nshape
    return g.emit("Reshape", [mm, g.const(np.asarray(out_shape, np.int64),
                                          "shape")])


def _zero_interleave(g, name, shape, axis, d, dtype, fill=0):
    """Insert d-1 `fill` elements between elements along `axis` (static
    shapes): [.., H, ..] -> [.., (H-1)*d+1, ..]. This is lax's lhs_dilation
    (transposed-conv fractional stride) / base_dilation (pooling) expressed
    in plain ONNX ops; `fill` is the reduction's identity (0 for conv/sum,
    -inf for max pooling)."""
    H = shape[axis]
    un_shape = list(shape[:axis + 1]) + [1] + list(shape[axis + 1:])
    x = g.emit("Reshape", [name, g.const(np.asarray(un_shape, np.int64),
                                         "shape")])
    z_shape = list(shape[:axis + 1]) + [d - 1] + list(shape[axis + 1:])
    zeros = g.const(np.full(z_shape, fill, dtype), "fill")
    x = g.emit("Concat", [x, zeros], axis=axis + 1)
    full = list(shape)
    full[axis] = H * d
    x = g.emit("Reshape", [x, g.const(np.asarray(full, np.int64), "shape")])
    starts = g.const(np.asarray([0], np.int64), "starts")
    ends = g.const(np.asarray([H * d - (d - 1)], np.int64), "ends")
    axes = g.const(np.asarray([axis], np.int64), "axes")
    steps = g.const(np.asarray([1], np.int64), "steps")
    x = g.emit("Slice", [x, starts, ends, axes, steps])
    full[axis] = (H - 1) * d + 1
    return x, full


def _emit_conv(g, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    spec = (dn.lhs_spec, dn.rhs_spec, dn.out_spec) if hasattr(dn, "lhs_spec") \
        else dn
    nd = len(p["window_strides"])
    iota = tuple(range(2 + nd))
    lname, rname = ins[0], ins[1]
    shape = [int(s) for s in eqn.invars[0].aval.shape]
    # non-NCHW/OIHW layouts (NHWC inputs, HWIO kernels, ...): the spec
    # tuples ARE the permutations onto canonical order — transpose in, run
    # the canonical Conv, transpose the output back per out_spec. Strides/
    # padding/dilations are already spatial-ordered and layout-independent.
    if tuple(spec[0]) != iota:
        perm = [int(d) for d in spec[0]]
        lname = g.emit("Transpose", [lname], perm=perm)
        shape = [shape[d] for d in perm]
    if tuple(spec[1]) != iota:
        rname = g.emit("Transpose", [rname], perm=[int(d) for d in spec[1]])
    ins = [lname, rname] + list(ins[2:])
    if any(d != 1 for d in p["lhs_dilation"]):
        # transposed conv: lax lowers it as a fractionally-strided conv
        # (lhs_dilation = stride). Decompose generically — zero-interleave
        # the input per spatial axis, then a plain Conv — instead of
        # pattern-matching our own lowering onto ConvTranspose.
        dtype = str(eqn.invars[0].aval.dtype)
        for i, d in enumerate(p["lhs_dilation"]):
            if d != 1:
                lname, shape = _zero_interleave(g, lname, shape, 2 + i,
                                                int(d), dtype)
    padding = [(int(lo), int(hi)) for lo, hi in p["padding"]]
    if any(lo < 0 or hi < 0 for lo, hi in padding):
        # XLA allows negative conv padding (a crop — Conv2DTranspose with
        # padding > k-1 lowers this way); ONNX Conv does not. Crop with a
        # Slice first, then clamp the pads to >= 0. `shape` already tracks
        # the post-interleave sizes.
        starts, ends, axes = [], [], []
        for i, (lo, hi) in enumerate(padding):
            if lo < 0 or hi < 0:
                ax = 2 + i
                starts.append(max(0, -lo))
                ends.append(shape[ax] - max(0, -hi))
                axes.append(ax)
        args = [lname] + [g.const(np.asarray(a, np.int64), h) for a, h in [
            (starts, "starts"), (ends, "ends"), (axes, "axes"),
            ([1] * len(axes), "steps")]]
        lname = g.emit("Slice", args)
        padding = [(max(0, lo), max(0, hi)) for lo, hi in padding]
    pads = [lo for lo, _ in padding] + [hi for _, hi in padding]
    conv = g.emit(
        "Conv", [lname] + ins[1:],
        strides=[int(s) for s in p["window_strides"]],
        dilations=[int(d) for d in p["rhs_dilation"]],
        pads=pads,
        group=int(p["feature_group_count"]))
    if tuple(spec[2]) != iota:
        # Conv emits canonical NCHW; out_spec[k] says where canonical dim k
        # lives in the jax output — the inverse permutation
        inv = [0] * (2 + nd)
        for k, d in enumerate(spec[2]):
            inv[int(d)] = k
        conv = g.emit("Transpose", [conv], perm=inv)
    return conv


def _emit_pool(g, eqn, ins, kind):
    p = eqn.params
    window = p["window_dimensions"]
    strides = p["window_strides"]
    padding = p["padding"]
    if len(window) < 3 or window[0] != 1 or window[1] != 1:
        raise NotImplementedError("onnx export: pool window not NCHW-spatial")
    dtype = str(eqn.invars[0].aval.dtype)
    src = ins[0]
    shape = [int(s) for s in eqn.invars[0].aval.shape]
    base_dil = [int(d) for d in
                p.get("base_dilation", [1] * len(window))]
    if any(d != 1 for d in base_dil):
        # base dilation interleaves the INPUT with the reduction identity
        # (-inf for max, 0 for sum) before windowing — same decomposition
        # as a transposed conv's fractional stride
        # the reduce identity: -inf (NOT finfo.min) for float max — with
        # base dilation > window size some windows see only fill, and lax
        # yields -inf there
        fill = (-np.inf if kind == "MaxPool" else 0) \
            if np.issubdtype(np.dtype(dtype), np.floating) \
            else (np.iinfo(dtype).min if kind == "MaxPool" else 0)
        for i, d in enumerate(base_dil):
            if d != 1:
                src, shape = _zero_interleave(g, src, shape, i, d, dtype,
                                              fill=fill)
    dil = [int(d) for d in p.get("window_dilation", [1] * len(window))][2:]
    kernel = [int(w) for w in window[2:]]
    pads = [int(pad[0]) for pad in padding[2:]] + \
           [int(pad[1]) for pad in padding[2:]]
    attrs = dict(kernel_shape=kernel, strides=[int(s) for s in strides[2:]],
                 pads=pads)
    if kind == "MaxPool":
        if any(d != 1 for d in dil):
            attrs["dilations"] = dil  # MaxPool grew dilations at opset 10
        return g.emit("MaxPool", [src], **attrs)
    if any(d != 1 for d in dil):
        # ONNX AveragePool only grows dilations at opset 19 (ours is 13):
        # a dilated window SUM is exactly a depthwise Conv with a ones
        # kernel [C,1,*k], group=C — Conv has dilations since opset 1
        C = shape[1]
        ones = g.const(np.ones([C, 1] + kernel, dtype), "ones_kernel")
        return g.emit("Conv", [src, ones], group=C, dilations=dil,
                      pads=pads, strides=attrs["strides"],
                      kernel_shape=kernel)
    # reduce_window_sum -> AveragePool(count_include_pad=1) * window_size
    avg = g.emit("AveragePool", [src], count_include_pad=1, **attrs)
    n = g.const(np.asarray(float(np.prod(kernel)), dtype), "window_elems")
    return g.emit("Mul", [avg, n])


def _inline(g, closed, outer_in, outer_out):
    jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
    consts = getattr(closed, "consts", getattr(closed, "literals", []))
    for cv, cval in zip(jaxpr.constvars, consts):
        g.alias(cv, g.const(np.asarray(cval)))
    for iv, ov in zip(jaxpr.invars, outer_in):
        g.alias(iv, g.name_of(ov))
    for eqn in jaxpr.eqns:
        _emit_eqn(g, eqn)
    from jax._src.core import Literal

    for inner_out, outer in zip(jaxpr.outvars, outer_out):
        if isinstance(inner_out, Literal):
            g.alias(outer, g.const(np.asarray(inner_out.val)))
        else:
            g.alias(outer, g.name_of(inner_out))


def export_jaxpr(closed_jaxpr, param_names, param_arrays, input_names,
                 opset_version=13, graph_name="paddle_tpu"):
    """ClosedJaxpr (invars = params then inputs) -> ONNX ModelProto bytes."""
    g = _Graph()
    jaxpr = closed_jaxpr.jaxpr
    for cv, cval in zip(jaxpr.constvars, closed_jaxpr.consts):
        g.alias(cv, g.const(np.asarray(cval)))
    n_params = len(param_names)
    for var, pname, arr in zip(jaxpr.invars[:n_params], param_names,
                               param_arrays):
        g.alias(var, pname)
        g.initializers.append(P.tensor(pname, np.ascontiguousarray(arr)))
    inputs = []
    for var, iname in zip(jaxpr.invars[n_params:], input_names):
        g.alias(var, iname)
        inputs.append(P.value_info(iname, _dtype_of(var.aval),
                                   var.aval.shape))
    for eqn in jaxpr.eqns:
        _emit_eqn(g, eqn)
    outputs = []
    for i, var in enumerate(jaxpr.outvars):
        outputs.append(P.value_info(g.name_of(var), _dtype_of(var.aval),
                                    var.aval.shape))
    gmsg = P.graph(graph_name, g.nodes, inputs, outputs, g.initializers)
    return P.model(gmsg, opset_version=opset_version)

"""paddle.onnx (reference python/paddle/onnx/export.py wraps paddle2onnx).

This build's native interchange format is StableHLO (paddle.jit.save) —
portable and runnable without model code. ONNX export additionally requires
the `onnx` package; when it's importable a minimal graph (inputs/outputs/
initializers via jit tracing) is emitted, otherwise a clear error points to
jit.save."""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "paddle.onnx.export needs the `onnx` package, which is not "
            "installed in this environment. Use paddle.jit.save for the "
            "portable StableHLO artifact instead.") from e
    raise NotImplementedError(
        "onnx emission is not implemented; use paddle.jit.save (StableHLO)")

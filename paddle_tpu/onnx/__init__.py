"""paddle.onnx — native ONNX export.

Reference: python/paddle/onnx/export.py (wraps paddle2onnx's op mappers).
TPU-native: the Layer's forward is traced to a jaxpr (the same trace jit
compiles), and each primitive maps to an ONNX op — so coverage follows the
primitive set, not a hand-enumerated layer list. The protobuf is hand-encoded
(paddle_tpu/onnx/_proto.py): no dependency on the `onnx` package. Models whose
forward uses unsupported primitives get a clear NotImplementedError pointing
to paddle.jit.save (StableHLO) as the full-fidelity alternative.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export `layer` to `{path}.onnx`.

    input_spec: list of InputSpec (concrete shapes) or example Tensors.
    Dynamic (None) dims are not supported — ONNX Reshape/Expand shape
    initializers are baked from the traced shapes.
    """
    import jax
    import numpy as np

    from ..core.tensor import Tensor
    from ..jit import functional_call
    from ._export import export_jaxpr

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec "
                         "(InputSpec list or example Tensors)")
    if opset_version < 13:
        raise ValueError(
            f"paddle.onnx.export emits opset-13 op forms (Slice/Squeeze/"
            f"ReduceSum with input-tensors); opset_version={opset_version} "
            f"would declare an opset the graph does not conform to")

    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(np.asarray(spec._data))
            continue
        shape = [int(d) if d is not None and int(d) != -1 else None
                 for d in spec.shape]
        if any(d is None for d in shape):
            raise ValueError(
                f"paddle.onnx.export: dynamic dim in {spec.shape} — ONNX "
                f"emission bakes shapes; pass concrete dims")
        dtype = getattr(spec, "dtype", "float32")
        examples.append(np.zeros(shape, str(dtype).replace("paddle.", "")))

    state = layer.state_dict(include_non_persistable_buffer=True)
    param_names = sorted(state.keys())
    param_arrays = [np.asarray(state[n]._data) for n in param_names]

    def fn(params, *inputs):
        out = functional_call(layer, dict(zip(param_names, params)),
                              *[Tensor(i) for i in inputs])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(o._data if isinstance(o, Tensor) else o for o in outs)

    closed = jax.make_jaxpr(fn)(param_arrays, *examples)
    input_names = [f"input_{i}" for i in range(len(examples))]
    blob = export_jaxpr(closed, param_names, param_arrays, input_names,
                        opset_version=opset_version,
                        graph_name=type(layer).__name__)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path

"""Profiler. Reference: python/paddle/profiler/profiler.py:270 (state-scheduler-driven Profiler,
chrome-trace export) + profiler/timer.py Benchmark (ips).

TPU-native: wraps jax.profiler (XPlane -> TensorBoard/perfetto) behind the same API; RecordEvent
maps to jax.profiler.TraceAnnotation so host markers interleave with device timelines.

Host-side events route through observability.tracer: aggregates (count/total/
max/min per name) feed summary() exactly as the old ``_event_stats`` dict did,
and while a trace window is open every span additionally lands in the tracer's
ring buffer and exports as genuine chrome-trace JSON next to the device trace.
"""
from __future__ import annotations

import enum
import json
import os
import sys
import time

from ..observability import tracer as _obs_tracer


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= period * repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready handler exporting the host chrome trace into dir_name.

    The directory is applied at Profiler CONSTRUCTION time (the handler
    carries it as ``export_dir``) — previously it was assigned on
    trace-ready, after _start_trace had already written the device trace to
    the old directory, so the requested dir was silently ignored.
    """

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        # one file per closed window — a cycling scheduler must not let a
        # later (possibly empty) window clobber an earlier export
        prof.export(os.path.join(dir_name, f"{name}_w{prof._windows}.json"))

    handler.export_dir = dir_name
    return handler


def reset_event_stats():
    _obs_tracer.get_tracer().clear_stats()


def get_event_stats():
    """name -> [count, total_s, max_s, min_s] for every RecordEvent seen
    since the last reset (the summary() data source)."""
    return _obs_tracer.get_tracer().stats()


class RecordEvent:
    """RAII marker (reference RecordEvent, platform/profiler/event_tracing.h):
    annotates the device trace AND records a host span (aggregate always;
    full timeline event while the tracer is enabled)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ta = None
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self._t0 = time.perf_counter()
        # annotate the device timeline only when jax is already loaded — a
        # host-only process pays nothing (observability disabled-path rule)
        if "jax" in sys.modules:
            try:
                import jax.profiler

                self._ta = jax.profiler.TraceAnnotation(self.name)
                self._ta.__enter__()
            except Exception:
                self._ta = None

    def end(self):
        if self._ta is not None:
            self._ta.__exit__(None, None, None)
            self._ta = None
        if self._t0 is not None:
            _obs_tracer.get_tracer().record_complete(
                self.name, self._t0, time.perf_counter())
            self._t0 = None


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 use_device_profiler=True):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=start, ready=0, record=end - start)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._windows = 0  # closed trace windows (distinct export files)
        self._use_device_profiler = use_device_profiler
        # handler-requested dir wins over the env default, and is applied
        # HERE so _start_trace targets it from the first trace window
        self._export_dir = getattr(on_trace_ready, "export_dir", None) or \
            os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._benchmark = Benchmark()

    def start(self):
        reset_event_stats()  # each profiling session aggregates its own events
        self._benchmark.begin()
        self._transition()

    def stop(self):
        if self._active:
            self._stop_trace()
        self._benchmark.end()

    def step(self, num_samples=None, reader_cost=None):
        self._benchmark.step(num_samples, reader_cost=reader_cost)
        self._step += 1
        self._transition()

    def _transition(self):
        if self._timer_only or self._scheduler is None:
            return
        new_state = self._scheduler(self._step)
        recording = new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        # RECORD_AND_RETURN covers the step ABOUT to run: its window closes at
        # the next transition (the seed closed it in the same transition it
        # opened, so a record=1 schedule exported an empty window)
        if self._active and (self._state == ProfilerState.RECORD_AND_RETURN
                             or not recording):
            self._stop_trace()
        if recording and not self._active:
            self._start_trace()
        self._state = new_state

    def _start_trace(self):
        tr = _obs_tracer.get_tracer()
        tr.clear()
        tr.enable()
        self._active = True
        if not self._use_device_profiler:
            return
        try:
            import jax.profiler

            os.makedirs(self._export_dir, exist_ok=True)
            jax.profiler.start_trace(self._export_dir)
            self._device_trace = True
        except Exception:
            self._device_trace = False

    def _stop_trace(self):
        if getattr(self, "_device_trace", False):
            try:
                import jax.profiler

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_trace = False
        _obs_tracer.get_tracer().disable()
        self._active = False
        self._windows += 1
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path=None, format="json"):
        """Write the host-span chrome trace (the device xplane/perfetto trace
        is exported by jax.profiler itself during stop_trace, same dir)."""
        if path is None:
            path = os.path.join(self._export_dir, f"host_{os.getpid()}.json")
        return _obs_tracer.get_tracer().export_chrome_trace(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        """Throughput line + RecordEvent aggregation table (reference
        profiler_statistic.py summary tables, host-event subset)."""
        info = self._benchmark.report()
        print(f"ips: {info.get('ips', 0.0):.2f} steps/s  reader_cost: "
              f"{info.get('reader_cost', 0.0) * 1000:.3f} ms")
        stats = get_event_stats()
        if not stats:
            return
        units = {"ms": 1e3, "us": 1e6, "s": 1.0}
        if time_unit not in units:
            raise ValueError(f"time_unit must be one of {sorted(units)}, "
                             f"got {time_unit!r}")
        unit = units[time_unit]
        # sort key per SortedKeys (host events: the CPU* keys apply)
        key_fns = {
            None: lambda st: -st[1],
            SortedKeys.CPUTotal: lambda st: -st[1],
            SortedKeys.CPUAvg: lambda st: -(st[1] / st[0]),
            SortedKeys.CPUMax: lambda st: -st[2],
            SortedKeys.CPUMin: lambda st: -st[3],
        }
        key = key_fns.get(sorted_by, key_fns[None])
        rows = sorted(stats.items(), key=lambda kv: key(kv[1]))
        w = max(len(n) for n, _ in rows) + 2
        print(f"{'Event':<{w}}{'Calls':>8}{'Total':>12}{'Avg':>12}"
              f"{'Max':>12}{'Min':>12}  ({time_unit})")
        for name, (cnt, tot, mx, mn) in rows:
            print(f"{name:<{w}}{cnt:>8}{tot * unit:>12.3f}"
                  f"{tot / cnt * unit:>12.3f}{mx * unit:>12.3f}"
                  f"{mn * unit:>12.3f}")


class Benchmark:
    """Throughput meter (reference profiler/timer.py:110). reader_cost is the
    tracked dataloader fetch time fed through step(reader_cost=...) by the
    hapi fit loop — it is no longer a hard-coded 0.0; report() averages it
    per step so summary() prints what was actually measured."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._steps = 0
        self._samples = 0
        self._reader_total = 0.0
        self._start = None
        self._last = None

    def begin(self):
        self._start = self._last = time.perf_counter()

    def step(self, num_samples=None, reader_cost=None):
        self._steps += 1
        if num_samples:
            self._samples += num_samples
        if reader_cost:
            self._reader_total += reader_cost
        self._last = time.perf_counter()

    def end(self):
        self._last = time.perf_counter()

    def report(self):
        if self._start is None or self._steps == 0:
            return {"ips": 0.0, "reader_cost": 0.0}
        elapsed = max(self._last - self._start, 1e-9)
        ips = (self._samples or self._steps) / elapsed
        return {"ips": ips, "reader_cost": self._reader_total / self._steps,
                "steps": self._steps, "elapsed": elapsed}


class ProfilerResult:
    """A loaded chrome trace: raw events plus the same per-name aggregate
    table summary() prints (reference LoadProfilerResult,
    profiler/profiler.py)."""

    def __init__(self, events, path=None):
        self.events = events  # [{"name", "ts_us", "dur_us", "tid", "pid", "args"}]
        self.path = path

    def stats(self):
        """name -> [count, total_s, max_s, min_s], matching
        get_event_stats() so round-tripped traces summarize identically."""
        out = {}
        for ev in self.events:
            dur = ev.get("dur_us")
            if dur is None:
                continue
            dur = dur / 1e6
            st = out.setdefault(ev["name"], [0, 0.0, 0.0, float("inf")])
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
            st[3] = min(st[3], dur)
        return out

    def time_range(self):
        """(min_ts_us, max_end_us) across complete events; (0, 0) if none."""
        spans = [(e["ts_us"], e["ts_us"] + (e.get("dur_us") or 0.0))
                 for e in self.events]
        if not spans:
            return (0.0, 0.0)
        return (min(s for s, _ in spans), max(e for _, e in spans))


def load_profiler_result(path):
    """Load an exported chrome-trace JSON (a file, or a directory holding
    *.json traces — multi-worker exports merge) back into a ProfilerResult."""
    paths = [path]
    if os.path.isdir(path):
        paths = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if f.endswith(".json"))
        if not paths:
            raise FileNotFoundError(f"no .json traces under {path!r}")
    events = []
    for p in paths:
        with open(p) as f:
            doc = json.load(f)
        raw = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
        if not isinstance(raw, list):
            raise ValueError(f"{p!r} is not a chrome trace "
                             "(no traceEvents array)")
        for ev in raw:
            ph = ev.get("ph")
            if ph not in ("X", "i", "I"):
                continue  # metadata / flow / counter events
            events.append({
                "name": ev.get("name", ""),
                "ts_us": float(ev.get("ts", 0.0)),
                "dur_us": float(ev["dur"]) if "dur" in ev else None,
                "tid": ev.get("tid"),
                "pid": ev.get("pid"),
                "args": ev.get("args") or {},
            })
    return ProfilerResult(events, path=path)


class SortedKeys(enum.Enum):
    """Summary-table sort keys (reference profiler/profiler_statistic.py)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name, worker_name=None):
    """Profiler on_trace_ready exporter (reference exports the paddle profiler
    proto; here the portable artifact is the chrome trace, same directory
    contract)."""

    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        prof.export(os.path.join(dir_name, f"{name}_w{prof._windows}.json"))

    handler.export_dir = dir_name
    return handler

"""Profiler. Reference: python/paddle/profiler/profiler.py:270 (state-scheduler-driven Profiler,
chrome-trace export) + profiler/timer.py Benchmark (ips).

TPU-native: wraps jax.profiler (XPlane -> TensorBoard/perfetto) behind the same API; RecordEvent
maps to jax.profiler.TraceAnnotation so host markers interleave with device timelines.
"""
from __future__ import annotations

import enum
import os
import time


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and s >= period * repeat:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        # jax.profiler writes xplane/perfetto under its own dir during stop
        prof._export_dir = dir_name

    return handler


# host-side event aggregation feeding Profiler.summary (the analogue of the
# reference's HostEventRecorder -> profiler_statistic tables)
_event_stats = {}  # name -> [count, total_s, max_s, min_s]


def reset_event_stats():
    _event_stats.clear()


class RecordEvent:
    """RAII marker (reference RecordEvent, platform/profiler/event_tracing.h):
    annotates the device trace AND aggregates host wall time for summary()."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ta = None
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            import jax.profiler

            self._ta = jax.profiler.TraceAnnotation(self.name)
            self._ta.__enter__()
        except Exception:
            self._ta = None

    def end(self):
        if self._ta is not None:
            self._ta.__exit__(None, None, None)
            self._ta = None
        if self._t0 is not None:
            dt = time.perf_counter() - self._t0
            st = _event_stats.setdefault(self.name, [0, 0.0, 0.0, float("inf")])
            st[0] += 1
            st[1] += dt
            st[2] = max(st[2], dt)
            st[3] = min(st[3], dt)
            self._t0 = None


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False):
        self._scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(closed=start, ready=0, record=end - start)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._export_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp/paddle_tpu_profile")
        self._benchmark = Benchmark()

    def start(self):
        reset_event_stats()  # each profiling session aggregates its own events
        self._benchmark.begin()
        self._transition()

    def stop(self):
        if self._active:
            self._stop_trace()
        self._benchmark.end()

    def step(self, num_samples=None):
        self._benchmark.step(num_samples)
        self._step += 1
        self._transition()

    def _transition(self):
        if self._timer_only or self._scheduler is None:
            return
        new_state = self._scheduler(self._step)
        recording = new_state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if recording and not self._active:
            self._start_trace()
        ret = new_state == ProfilerState.RECORD_AND_RETURN
        if self._active and (not recording or ret):
            self._stop_trace()

    def _start_trace(self):
        try:
            import jax.profiler

            os.makedirs(self._export_dir, exist_ok=True)
            jax.profiler.start_trace(self._export_dir)
            self._active = True
        except Exception:
            self._active = False

    def _stop_trace(self):
        try:
            import jax.profiler

            jax.profiler.stop_trace()
        except Exception:
            pass
        self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path=None, format="json"):
        pass  # traces already exported by stop_trace

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        """Throughput line + RecordEvent aggregation table (reference
        profiler_statistic.py summary tables, host-event subset)."""
        info = self._benchmark.report()
        print(f"ips: {info.get('ips', 0.0):.2f} steps/s  reader_cost: "
              f"{info.get('reader_cost', 0.0) * 1000:.3f} ms")
        if not _event_stats:
            return
        units = {"ms": 1e3, "us": 1e6, "s": 1.0}
        if time_unit not in units:
            raise ValueError(f"time_unit must be one of {sorted(units)}, "
                             f"got {time_unit!r}")
        unit = units[time_unit]
        # sort key per SortedKeys (host events: the CPU* keys apply)
        key_fns = {
            None: lambda st: -st[1],
            SortedKeys.CPUTotal: lambda st: -st[1],
            SortedKeys.CPUAvg: lambda st: -(st[1] / st[0]),
            SortedKeys.CPUMax: lambda st: -st[2],
            SortedKeys.CPUMin: lambda st: -st[3],
        }
        key = key_fns.get(sorted_by, key_fns[None])
        rows = sorted(_event_stats.items(), key=lambda kv: key(kv[1]))
        w = max(len(n) for n, _ in rows) + 2
        print(f"{'Event':<{w}}{'Calls':>8}{'Total':>12}{'Avg':>12}"
              f"{'Max':>12}{'Min':>12}  ({time_unit})")
        for name, (cnt, tot, mx, mn) in rows:
            print(f"{name:<{w}}{cnt:>8}{tot * unit:>12.3f}"
                  f"{tot / cnt * unit:>12.3f}{mx * unit:>12.3f}"
                  f"{mn * unit:>12.3f}")


class Benchmark:
    """Throughput meter (reference profiler/timer.py:110)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._steps = 0
        self._samples = 0
        self._start = None
        self._last = None

    def begin(self):
        self._start = self._last = time.perf_counter()

    def step(self, num_samples=None):
        self._steps += 1
        if num_samples:
            self._samples += num_samples
        self._last = time.perf_counter()

    def end(self):
        self._last = time.perf_counter()

    def report(self):
        if self._start is None or self._steps == 0:
            return {"ips": 0.0, "reader_cost": 0.0}
        elapsed = max(self._last - self._start, 1e-9)
        ips = (self._samples or self._steps) / elapsed
        return {"ips": ips, "reader_cost": 0.0, "steps": self._steps,
                "elapsed": elapsed}


def load_profiler_result(path):
    raise NotImplementedError


class SortedKeys(enum.Enum):
    """Summary-table sort keys (reference profiler/profiler_statistic.py)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name, worker_name=None):
    """Profiler on_trace_ready exporter (reference exports the paddle profiler
    proto; here the portable artifact is the chrome trace, same directory
    contract)."""

    def handler(prof):
        prof.export(dir_name, format="json")

    return handler

"""Elementwise / binary / scalar math ops.

Reference parity: python/paddle/tensor/math.py + phi elementwise kernels
(paddle/phi/kernels/elementwise_*ized). All lower to jnp/lax, which XLA fuses on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import apply, as_tensor
from ..core.tensor import Tensor
from ._helpers import binary, unary, t_

# ---- binary arithmetic ----
add = binary("add", jnp.add)
subtract = binary("subtract", jnp.subtract)
multiply = binary("multiply", jnp.multiply)
divide = binary("divide", jnp.true_divide)
floor_divide = binary("floor_divide", jnp.floor_divide, differentiable=False)
remainder = binary("remainder", jnp.remainder)
mod = remainder
floor_mod = remainder
pow = binary("pow", jnp.power)
maximum = binary("maximum", jnp.maximum)
minimum = binary("minimum", jnp.minimum)
fmax = binary("fmax", jnp.fmax)
fmin = binary("fmin", jnp.fmin)
atan2 = binary("atan2", jnp.arctan2)
hypot = binary("hypot", jnp.hypot)
copysign = binary("copysign", jnp.copysign)
nextafter = binary("nextafter", jnp.nextafter, differentiable=False)
ldexp = binary("ldexp", jnp.ldexp)
logaddexp = binary("logaddexp", jnp.logaddexp)
heaviside = binary("heaviside", jnp.heaviside)
gcd = binary("gcd", jnp.gcd, differentiable=False)
lcm = binary("lcm", jnp.lcm, differentiable=False)
kron = binary("kron", jnp.kron)
inner = binary("inner", jnp.inner)
outer = binary("outer", lambda a, b: jnp.outer(a, b))

# ---- comparisons (never differentiable) ----
equal = binary("equal", jnp.equal, differentiable=False)
not_equal = binary("not_equal", jnp.not_equal, differentiable=False)
less_than = binary("less_than", jnp.less, differentiable=False)
less_equal = binary("less_equal", jnp.less_equal, differentiable=False)
greater_than = binary("greater_than", jnp.greater, differentiable=False)
greater_equal = binary("greater_equal", jnp.greater_equal, differentiable=False)
logical_and = binary("logical_and", jnp.logical_and, differentiable=False)
logical_or = binary("logical_or", jnp.logical_or, differentiable=False)
logical_xor = binary("logical_xor", jnp.logical_xor, differentiable=False)
bitwise_and = binary("bitwise_and", jnp.bitwise_and, differentiable=False)
bitwise_or = binary("bitwise_or", jnp.bitwise_or, differentiable=False)
bitwise_xor = binary("bitwise_xor", jnp.bitwise_xor, differentiable=False)
bitwise_left_shift = binary("bitwise_left_shift", jnp.left_shift, differentiable=False)
bitwise_right_shift = binary("bitwise_right_shift", jnp.right_shift, differentiable=False)


def logical_not(x, name=None):
    return apply("logical_not", jnp.logical_not, [t_(x)], differentiable=False)


def bitwise_not(x, name=None):
    return apply("bitwise_not", jnp.bitwise_not, [t_(x)], differentiable=False)


# ---- unary ----
exp = unary("exp", jnp.exp)
expm1 = unary("expm1", jnp.expm1)
log = unary("log", jnp.log)
log2 = unary("log2", jnp.log2)
log10 = unary("log10", jnp.log10)
log1p = unary("log1p", jnp.log1p)
sqrt = unary("sqrt", jnp.sqrt)
rsqrt = unary("rsqrt", lambda x: jax.lax.rsqrt(x))
square = unary("square", jnp.square)
reciprocal = unary("reciprocal", lambda x: 1.0 / x)
abs = unary("abs", jnp.abs)
neg = unary("neg", jnp.negative)
sin = unary("sin", jnp.sin)
cos = unary("cos", jnp.cos)
tan = unary("tan", jnp.tan)
asin = unary("asin", jnp.arcsin)
acos = unary("acos", jnp.arccos)
atan = unary("atan", jnp.arctan)
sinh = unary("sinh", jnp.sinh)
cosh = unary("cosh", jnp.cosh)
tanh = unary("tanh", jnp.tanh)
asinh = unary("asinh", jnp.arcsinh)
acosh = unary("acosh", jnp.arccosh)
atanh = unary("atanh", jnp.arctanh)
erf = unary("erf", jax.scipy.special.erf)
erfinv = unary("erfinv", jax.scipy.special.erfinv)
floor = unary("floor", jnp.floor)
ceil = unary("ceil", jnp.ceil)
round = unary("round", jnp.round)
trunc = unary("trunc", jnp.trunc)
frac = unary("frac", lambda x: x - jnp.trunc(x))
sign = unary("sign", jnp.sign)
sgn = sign
digamma = unary("digamma", jax.scipy.special.digamma)
lgamma = unary("lgamma", jax.scipy.special.gammaln)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
logit = unary("logit", lambda x: jnp.log(x) - jnp.log1p(-x))
i0 = unary("i0", lambda x: jax.scipy.special.i0(x))
i1 = unary("i1", lambda x: jax.scipy.special.i1(x))
isnan = unary("isnan", jnp.isnan, differentiable=False)
isinf = unary("isinf", jnp.isinf, differentiable=False)
isfinite = unary("isfinite", jnp.isfinite, differentiable=False)
conj = unary("conj", jnp.conj)
real = unary("real", jnp.real)
imag = unary("imag", jnp.imag)
angle = unary("angle", jnp.angle)
deg2rad = unary("deg2rad", jnp.deg2rad)
rad2deg = unary("rad2deg", jnp.rad2deg)
exponent = unary("exponent", lambda x: jnp.frexp(x)[1].astype(jnp.int32), differentiable=False)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def k(a, scale, bias, bias_after_scale):
        if bias_after_scale:
            return a * scale + bias
        return (a + bias) * scale

    out = apply("scale", k, [t_(x)],
                {"scale": float(scale) if not isinstance(scale, Tensor) else scale.item(),
                 "bias": float(bias), "bias_after_scale": bool(bias_after_scale)})
    if act:
        from . import activation as _act
        out = getattr(_act, act)(out)
    return out


def increment(x, value=1.0, name=None):
    out = apply("increment", lambda a, value: a + value, [t_(x)], {"value": value})
    x.set_value(out._data)
    return x


def clip(x, min=None, max=None, name=None):
    def _v(v):
        return v.item() if isinstance(v, Tensor) else v

    return apply("clip", lambda a, lo, hi: jnp.clip(a, lo, hi), [t_(x)],
                 {"lo": _v(min), "hi": _v(max)})


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), [t_(x), t_(y), weight])
    return apply("lerp", lambda a, b, weight: a + weight * (b - a), [t_(x), t_(y)],
                 {"weight": weight})


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num", lambda a, nan, posinf, neginf: jnp.nan_to_num(
        a, nan=nan, posinf=posinf, neginf=neginf), [t_(x)],
        {"nan": nan, "posinf": posinf, "neginf": neginf})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda a, scale_a, scale_b: scale_b * jnp.tanh(scale_a * a),
                 [t_(x)], {"scale_a": scale_a, "scale_b": scale_b})


def multiplex(inputs, index, name=None):
    stacked = jnp.stack([t_(i)._data for i in inputs], 1)  # [N, num_ins, ...]
    idx = t_(index)._data.reshape(-1)
    return Tensor(jnp.take_along_axis(
        stacked, idx.reshape(-1, 1, *([1] * (stacked.ndim - 2))), axis=1).squeeze(1))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(t_(x)._data, t_(y)._data, rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply("isclose", lambda a, b, rtol, atol, equal_nan: jnp.isclose(
        a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), [t_(x), t_(y)],
        {"rtol": rtol, "atol": atol, "equal_nan": equal_nan}, differentiable=False)


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(t_(x)._data, t_(y)._data))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm", lambda i, a, b, beta, alpha: beta * i + alpha * (a @ b),
                 [t_(input), t_(x), t_(y)], {"beta": beta, "alpha": alpha})


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda a, offset, axis1, axis2: jnp.trace(a, offset, axis1, axis2),
                 [t_(x)], {"offset": offset, "axis1": axis1, "axis2": axis2})


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal", lambda a, offset, axis1, axis2: jnp.diagonal(a, offset, axis1, axis2),
                 [t_(x)], {"offset": offset, "axis1": axis1, "axis2": axis2})


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("cumsum", lambda a, axis, dtype: jnp.cumsum(
        a if axis is not None else a.reshape(-1), axis=axis if axis is not None else 0,
        dtype=dtype), [t_(x)], {"axis": axis, "dtype": d})


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("cumprod", lambda a, dim, dtype: jnp.cumprod(a, axis=dim, dtype=dtype),
                 [t_(x)], {"dim": dim, "dtype": d})


def cummax(x, axis=None, dtype="int64", name=None):
    x = t_(x)
    a = x._data if axis is not None else x._data.reshape(-1)
    ax = axis if axis is not None else 0
    n = a.shape[ax]
    ar = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(a.ndim)])
    ar = jnp.broadcast_to(ar, a.shape)

    def mx(l, r):
        lv, li = l
        rv, ri = r
        take_r = rv >= lv
        return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

    vals, inds = jax.lax.associative_scan(mx, (a, ar), axis=ax)
    return Tensor(vals), Tensor(inds.astype(dtypes.convert_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    x = t_(x)
    a = x._data if axis is not None else x._data.reshape(-1)
    ax = axis if axis is not None else 0
    n = a.shape[ax]
    ar = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(a.ndim)])
    ar = jnp.broadcast_to(ar, a.shape)

    def mn(l, r):
        lv, li = l
        rv, ri = r
        take_r = rv <= lv
        return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

    vals, inds = jax.lax.associative_scan(mn, (a, ar), axis=ax)
    return Tensor(vals), Tensor(inds.astype(dtypes.convert_dtype(dtype)))


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = t_(x)

    def k(a, axis):
        if axis is None:
            a = a.reshape(-1)
            axis = 0
        return jax.lax.cumlogsumexp(a, axis=axis)

    return apply("logcumsumexp", k, [x], {"axis": axis})


def rsqrt_(x):
    x.set_value(jax.lax.rsqrt(x._data))
    return x


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference: sum_op / paddle.add_n)."""
    if isinstance(inputs, Tensor):
        # single tensor: a fresh output tensor, never an alias of the input
        return apply("add_n", lambda a: a, [inputs])

    def k(*xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out

    return apply("add_n", k, [t_(i) for i in inputs])


def renorm(x, p, axis, max_norm, name=None):
    """Clamp the p-norm of every slice along `axis` to at most max_norm."""

    def k(a, p, axis, max_norm):
        other = tuple(i for i in range(a.ndim) if i != axis)
        norms = jnp.sum(jnp.abs(a) ** p, axis=other, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor

    ax = axis + t_(x).ndim if axis < 0 else axis
    return apply("renorm", k, [t_(x)], {"p": float(p), "axis": ax,
                                        "max_norm": float(max_norm)})


def complex(real, imag, name=None):
    return apply("complex", jax.lax.complex, [t_(real), t_(imag)])

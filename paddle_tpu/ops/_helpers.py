"""Op-definition helpers (the analogue of phi's kernel registration macros)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply, as_tensor, register_kernel
from ..core.tensor import Tensor

_SCALARS = (bool, int, float, complex)


def unary(name, jfn, differentiable=True):
    @register_kernel(name)
    def kernel(x, **attrs):
        return jfn(x, **attrs)

    def op(x, name_=None, **attrs):
        return apply(name, kernel, [as_tensor(x)], attrs, differentiable=differentiable)

    op.__name__ = name
    return op


def binary(name, jfn, differentiable=True):
    """Binary op with weak-typed python-scalar fast path (keeps bf16 under AMP)."""

    @register_kernel(name)
    def kernel(x, y, **attrs):
        return jfn(x, y, **attrs)

    def op(x, y, name_=None, **attrs):
        if isinstance(y, _SCALARS) and isinstance(x, Tensor):
            return apply(
                name, lambda a, _s=y, **at: jfn(a, _s, **at), [x], attrs,
                differentiable=differentiable,
            )
        if isinstance(x, _SCALARS) and isinstance(y, Tensor):
            return apply(
                name, lambda b, _s=x, **at: jfn(_s, b, **at), [y], attrs,
                differentiable=differentiable,
            )
        return apply(
            name, kernel, [as_tensor(x), as_tensor(y)], attrs,
            differentiable=differentiable,
        )

    op.__name__ = name
    return op


def normalize_axis(axis, ndim):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(normalize_axis(a, ndim) for a in axis)
    axis = int(axis)
    if axis < 0:
        axis += ndim
    return axis


def t_(x):
    return as_tensor(x)

"""NN functional ops: conv/pool/norm/dropout/embedding/losses/attention.

Reference parity: python/paddle/nn/functional/* lowering to phi conv/pool/norm kernels
(paddle/phi/kernels/gpu/conv_kernel.cu etc). TPU-native: convs lower to
`lax.conv_general_dilated` (MXU), pools to `lax.reduce_window`; data_format NCHW (paddle default)
is accepted and handed to XLA via dimension_numbers — no transposes inserted.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core import flags
from ..core import random as random_mod
from ..core.dispatch import apply, as_tensor
from ..core.tensor import Tensor
from ._helpers import normalize_axis, t_


def _pair(v, n):
    if isinstance(v, (int, float)):
        return (v,) * n
    v = tuple(v)
    if len(v) == 1:
        return v * n
    return v


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return apply("linear", lambda a, w: a @ w, [t_(x), t_(weight)])
    return apply("linear", lambda a, w, b: a @ w + b, [t_(x), t_(weight), t_(bias)])


# ---------- convolution ----------

def _conv_dn(ndim, channel_last):
    if ndim == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if ndim == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(p) for p in padding]


def _convnd(name, nd, x, weight, bias, stride, padding, dilation, groups, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    dn = _conv_dn(nd, channel_last)
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    pad = _conv_padding(padding, nd)

    def kernel(a, w, *maybe_bias):
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if maybe_bias:
            b = maybe_bias[0]
            if channel_last:
                out = out + b
            else:
                out = out + b.reshape((1, -1) + (1,) * nd)
        return out

    args = [t_(x), t_(weight)] + ([t_(bias)] if bias is not None else [])
    return apply(name, kernel, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _convnd("conv1d", 1, x, weight, bias, stride, padding, dilation, groups, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _convnd("conv2d", 2, x, weight, bias, stride, padding, dilation, groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _convnd("conv3d", 3, x, weight, bias, stride, padding, dilation, groups, data_format)


def _conv_transpose(name, nd, x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, output_size=None):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    dn = _conv_dn(nd, channel_last)
    stride = _pair(stride, nd)
    dilation = _pair(dilation, nd)
    pad = _conv_padding(padding, nd)
    out_pad = _pair(output_padding or 0, nd)

    def kernel(a, w, *maybe_bias):
        # paddle weight layout for transpose conv: [in, out//groups, *k] ==> grad-conv form.
        # Use conv_transpose via conv_general_dilated with lhs dilation.
        k_spatial = w.shape[2:]
        if isinstance(pad, str):
            pads = None
        else:
            pads = []
            for i in range(nd):
                lo = dilation[i] * (k_spatial[i] - 1) - pad[i][0]
                hi = dilation[i] * (k_spatial[i] - 1) - pad[i][1] + out_pad[i]
                pads.append((lo, hi))
        # flip spatial dims and swap in/out channels: [in, out//g, *k] -> [out, in//g, *k]
        w_t = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
        if groups > 1:
            # [in, out//g, *k] -> g groups of [in//g, out//g, *k]
            w_t = w_t.reshape((groups, w.shape[0] // groups) + w_t.shape[1:])
            w_t = jnp.swapaxes(w_t, 1, 2)  # [g, out//g, in//g, *k]
            w_t = w_t.reshape((w.shape[1] * groups, w.shape[0] // groups) + k_spatial)
        else:
            w_t = jnp.swapaxes(w_t, 0, 1)
        out = jax.lax.conv_general_dilated(
            a, w_t, window_strides=(1,) * nd,
            padding=pads if pads is not None else "SAME",
            lhs_dilation=stride, rhs_dilation=dilation,
            dimension_numbers=dn, feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            out = out + (b if channel_last else b.reshape((1, -1) + (1,) * nd))
        return out

    args = [t_(x), t_(weight)] + ([t_(bias)] if bias is not None else [])
    return apply(name, kernel, args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _conv_transpose("conv1d_transpose", 1, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, df)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose("conv2d_transpose", 2, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose("conv3d_transpose", 3, x, weight, bias, stride, padding,
                           output_padding, dilation, groups, data_format)


# ---------- pooling ----------

def _pool(name, x, kernel_size, stride, padding, nd, reducer, init, data_format,
          ceil_mode=False, exclusive=True, count_include_pad=False):
    x = t_(x)
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _conv_padding(padding, nd)
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + (pd if not isinstance(pd, str) else pd) + [(0, 0)] if not isinstance(pd, str) else pd
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + pd if not isinstance(pd, str) else pd

    def kernel(a):
        if reducer == "max":
            return jax.lax.reduce_window(a, -jnp.inf if dtypes.is_floating(a.dtype) else jnp.iinfo(a.dtype).min,
                                         jax.lax.max, window, strides,
                                         pads if not isinstance(pads, str) else pads)
        # avg
        ones = jnp.ones_like(a)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, window, strides,
                                  pads if not isinstance(pads, str) else pads)
        if count_include_pad:
            denom = float(np.prod(ks))
            return s / denom
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                    pads if not isinstance(pads, str) else pads)
        return s / cnt

    return apply(name, kernel, [x])


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCL", name=None):
    if return_mask:
        return _max_pool_with_indices("max_pool1d_with_index", x, kernel_size,
                                      stride, padding, 1)
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool("max_pool1d", x, kernel_size, stride, padding, 1, "max", None, df, ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_indices("max_pool2d_with_index", x, kernel_size,
                                      stride, padding, 2)
    return _pool("max_pool2d", x, kernel_size, stride, padding, 2, "max", None, data_format, ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_indices("max_pool3d_with_index", x, kernel_size,
                                      stride, padding, 3)
    return _pool("max_pool3d", x, kernel_size, stride, padding, 3, "max", None, data_format, ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               data_format="NCL", name=None):
    df = "NWC" if data_format == "NLC" else "NCW"
    return _pool("avg_pool1d", x, kernel_size, stride, padding, 1, "avg", None, df, ceil_mode,
                 exclusive, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool("avg_pool2d", x, kernel_size, stride, padding, 2, "avg", None, data_format,
                 ceil_mode, exclusive, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool("avg_pool3d", x, kernel_size, stride, padding, 3, "avg", None, data_format,
                 ceil_mode, exclusive, count_include_pad=not exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = t_(x)
    out_hw = _pair(output_size, 2)
    channel_last = data_format == "NHWC"
    h_ax, w_ax = (1, 2) if channel_last else (2, 3)
    in_h, in_w = x.shape[h_ax], x.shape[w_ax]
    if out_hw[0] is None:
        out_hw = (in_h, out_hw[1])
    if out_hw[1] is None:
        out_hw = (out_hw[0], in_w)
    if in_h % out_hw[0] == 0 and in_w % out_hw[1] == 0:
        kh, kw = in_h // out_hw[0], in_w // out_hw[1]
        return avg_pool2d(x, (kh, kw), (kh, kw), 0, data_format=data_format)

    def kernel(a):
        # general adaptive: mean over variable windows via cumulative sums
        def pool_axis(arr, axis, out_sz):
            in_sz = arr.shape[axis]
            starts = (np.arange(out_sz) * in_sz) // out_sz
            ends = ((np.arange(out_sz) + 1) * in_sz + out_sz - 1) // out_sz
            pieces = [jnp.mean(jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                               axis=axis, keepdims=True) for s, e in zip(starts, ends)]
            return jnp.concatenate(pieces, axis=axis)

        return pool_axis(pool_axis(a, h_ax, out_hw[0]), w_ax, out_hw[1])

    return apply("adaptive_avg_pool2d", kernel, [x])


def adaptive_avg_pool1d(x, output_size, name=None):
    x = t_(x)
    out = adaptive_avg_pool2d(unsq := apply("unsqueeze", lambda a: jnp.expand_dims(a, -1), [x]),
                              (output_size, 1))
    return apply("squeeze", lambda a: jnp.squeeze(a, -1), [out])


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = t_(x)
    out_hw = _pair(output_size, 2)
    in_h, in_w = x.shape[2], x.shape[3]
    if in_h % out_hw[0] == 0 and in_w % out_hw[1] == 0:
        kh, kw = in_h // out_hw[0], in_w // out_hw[1]
        return max_pool2d(x, (kh, kw), (kh, kw), 0)

    def kernel(a):
        def pool_axis(arr, axis, out_sz):
            in_sz = arr.shape[axis]
            starts = (np.arange(out_sz) * in_sz) // out_sz
            ends = ((np.arange(out_sz) + 1) * in_sz + out_sz - 1) // out_sz
            pieces = [jnp.max(jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                              axis=axis, keepdims=True) for s, e in zip(starts, ends)]
            return jnp.concatenate(pieces, axis=axis)

        return pool_axis(pool_axis(a, 2, out_hw[0]), 3, out_hw[1])

    return apply("adaptive_max_pool2d", kernel, [x])


# ---------- normalization ----------

def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    x = t_(x)
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else (1 if x.ndim > 1 else 0)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    def kernel(a, *params):
        i = 0
        if use_batch_stats:
            m = jnp.mean(a, axis=reduce_axes)
            v = jnp.var(a, axis=reduce_axes)
        else:
            m = running_mean._data
            v = running_var._data
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        out = (a - m.reshape(shape)) * jax.lax.rsqrt(v.reshape(shape) + epsilon)
        if weight is not None:
            out = out * params[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + params[i].reshape(shape)
        if use_batch_stats:
            # expose batch stats so the stateful running-stat update reuses this
            # single reduction (one fused XLA computation, no second pass)
            return out, m, v
        return out

    args = [x] + [t_(p) for p in (weight, bias) if p is not None]
    result = apply("batch_norm", kernel, args)
    if not use_batch_stats:
        return result
    out, bm, bv = result
    # stateful running-stat update (the reference's batch_norm op side outputs).
    # Inside a trace this stores traced arrays into the (swapped) buffer tensors;
    # functional_call_with_state reads them out as the step's new buffer state,
    # and _swapped_state restores the eager originals afterwards.
    if running_mean is not None:
        running_mean.set_value(momentum * running_mean._data + (1 - momentum) * bm._data)
    if running_var is not None:
        n = x._data.size / x._data.shape[ch_axis]
        unbiased = bv._data * (n / builtins_max(n - 1, 1))
        running_var.set_value(momentum * running_var._data + (1 - momentum) * unbiased)
    return out


def builtins_max(a, b):
    return a if a > b else b


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    x = t_(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    axes = tuple(range(x.ndim - n_axes, x.ndim))

    # The Pallas LayerNorm kernel is RETIRED from this route (BASELINE.md
    # round 5: never completed a functional on-chip run across two chip
    # windows, and XLA already fuses this lowering into the surrounding
    # elementwise chain — the kernel remains a direct-call library op in
    # ops/pallas/layer_norm.py, math pinned by tests/test_pallas_layernorm).
    def kernel(a, *params):
        m = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        v = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon)).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * params[i]
            i += 1
        if bias is not None:
            out = out + params[i]
        return out

    args = [x] + [t_(p) for p in (weight, bias) if p is not None]
    return apply("layer_norm", kernel, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    x = t_(x)

    def kernel(a, *params):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if params:
            out = out * params[0]
        return out

    args = [x] + ([t_(weight)] if weight is not None else [])
    return apply("rms_norm", kernel, args)


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5, data_format="NCHW", name=None):
    x = t_(x)
    channel_last = data_format in ("NHWC", "NLC", "NWC", "NDHWC")
    ch_axis = x.ndim - 1 if channel_last else 1
    c = x.shape[ch_axis]

    def kernel(a, *params):
        if channel_last:
            a_g = jnp.moveaxis(a, -1, 1)
        else:
            a_g = a
        n = a_g.shape[0]
        g = a_g.reshape((n, num_groups, c // num_groups) + a_g.shape[2:])
        axes = tuple(range(2, g.ndim))
        m = jnp.mean(g, axis=axes, keepdims=True)
        v = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_g.shape)
        shape = [1] * a_g.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * params[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + params[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x] + [t_(p) for p in (weight, bias) if p is not None]
    return apply("group_norm", kernel, args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    x = t_(x)
    axes = tuple(range(2, x.ndim))

    def kernel(a, *params):
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) * jax.lax.rsqrt(v + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * params[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + params[i].reshape(shape)
        return out

    args = [x] + [t_(p) for p in (weight, bias) if p is not None]
    return apply("instance_norm", kernel, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def kernel(a):
        sq = jnp.square(a)
        half = size // 2
        pad = [(0, 0)] * a.ndim
        pad[1] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pad)
        win = sum(jax.lax.slice_in_dim(sq_p, i, i + a.shape[1], axis=1) for i in range(size))
        return a / jnp.power(k + alpha * win, beta)

    return apply("local_response_norm", kernel, [t_(x)])


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def kernel(a, p, axis, epsilon):
        n = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=True), 1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return apply("normalize", kernel, [t_(x)], {"p": p, "axis": axis, "epsilon": epsilon})


# ---------- dropout / embedding ----------

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = t_(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout_scale", lambda a: a * (1 - p), [x])
        return x
    if p == 1.0:
        return apply("dropout", lambda a: jnp.zeros_like(a), [x])
    key = random_mod.next_key()
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    mask = jax.random.bernoulli(key, 1.0 - p, tuple(shape))

    def kernel(a):
        if mode == "upscale_in_train":
            return jnp.where(mask, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(mask, a, 0.0).astype(a.dtype)

    return apply("dropout", kernel, [x])


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = t_(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = random_mod.next_key()
    mask = jax.random.bernoulli(key, 1.0 - p, tuple(x.shape))
    a_coef = (1.0 - p + p * alpha_p ** 2) ** -0.5
    b_coef = -a_coef * p * alpha_p

    def kernel(v):
        return (a_coef * jnp.where(mask, v, alpha_p) + b_coef).astype(v.dtype)

    return apply("alpha_dropout", kernel, [x])


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = t_(x), t_(weight)

    def kernel(ids, w):
        out = jnp.take(w, ids, axis=0)
        if padding_idx is not None:
            out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
        return out

    return apply("embedding", kernel, [x, weight], nondiff_mask=[True, False])


def one_hot(x, num_classes, name=None):
    return apply("one_hot", lambda a, n: jax.nn.one_hot(a, n, dtype=jnp.float32),
                 [t_(x)], {"n": int(num_classes)}, differentiable=False)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = t_(label)

    def kernel(l, *pd):
        k = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / k

    args = [label] + ([t_(prior_dist)] if prior_dist is not None else [])
    return apply("label_smooth", kernel, args)


# ---------- losses ----------

def _reduce_loss(loss_t, reduction):
    from . import reduction as R

    if reduction == "mean":
        return R.mean(loss_t)
    if reduction == "sum":
        return R.sum(loss_t)
    return loss_t


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    logits, label = t_(logits), t_(label)

    def kernel(lg, lb):
        lsm = jax.nn.log_softmax(lg.astype(jnp.float32), axis=axis)
        if soft_label:
            loss = -jnp.sum(lb * lsm, axis=axis, keepdims=True)
        else:
            lb_ = lb
            if lb_.ndim == lg.ndim:
                lb_ = jnp.squeeze(lb_, axis)
            safe = jnp.where(lb_ == ignore_index, 0, lb_)
            picked = jnp.take_along_axis(lsm, jnp.expand_dims(safe, axis), axis=axis)
            loss = -picked
            loss = jnp.where(jnp.expand_dims(lb_ == ignore_index, axis), 0.0, loss)
        return loss.astype(lg.dtype)

    nondiff = [False, not soft_label]
    loss = apply("softmax_with_cross_entropy", kernel, [logits, label], nondiff_mask=nondiff)
    if return_softmax:
        from .activation import softmax as _softmax

        return loss, _softmax(logits, axis=axis)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    input, label = t_(input), t_(label)
    smoothed_ignore_mask = None
    if label_smoothing > 0.0 and not soft_label:
        num_classes = input.shape[axis]
        # normalize paddle's hard-label conventions BEFORE one_hot: a
        # trailing singleton class slot ((N, 1) labels, or (..., 1) at
        # `axis`) must squeeze away, or one_hot would broadcast a bogus
        # cross-pairing through the soft kernel
        if len(label.shape) == len(input.shape) and \
                label.shape[axis % len(input.shape)] == 1:
            label = Tensor(jnp.squeeze(label._data, axis % len(input.shape)))
        # remember which rows were padding BEFORE smoothing turns their
        # all-zero one-hot into a uniform eps/K distribution — ALL reductions
        # below must keep excluding them, weighted or not
        smoothed_ignore_mask = Tensor(
            (label._data == ignore_index).astype(jnp.float32))
        label = one_hot(label, num_classes)
        label = label_smooth(label, epsilon=label_smoothing)
        if axis % len(input.shape) != len(input.shape) - 1:
            # one_hot/label_smooth work with classes on the LAST axis; the
            # soft kernels reduce over `axis` — line the two up
            label = Tensor(jnp.moveaxis(label._data, -1,
                                        axis % len(input.shape)))
        soft_label = True

    if not use_softmax:
        def kernel(p, lb, *w):
            logp = jnp.log(jnp.clip(p, 1e-10, 1.0))
            if soft_label:
                loss = -jnp.sum(lb * logp, axis=axis, keepdims=True)
            else:
                lb_ = lb if lb.ndim < p.ndim else jnp.squeeze(lb, axis)
                safe = jnp.where(lb_ == ignore_index, 0, lb_)
                loss = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
                loss = jnp.where(jnp.expand_dims(lb_ == ignore_index, axis), 0.0, loss)
            return loss

        loss = apply("cross_entropy_prob", kernel, [input, label],
                     nondiff_mask=[False, not soft_label])
    else:
        loss = softmax_with_cross_entropy(input, label, soft_label=soft_label,
                                          ignore_index=ignore_index, axis=axis)

    if weight is not None and soft_label:
        # reference semantics (nn/functional/loss.py:1769): the UNWEIGHTED
        # per-sample soft loss scales by weight_gather = sum_c w_c*label_c,
        # and mean reduction divides by sum(weight_gather). Built from
        # Tensor ops so input AND label gradients keep flowing through the
        # already-computed loss (which used the f32-upcast kernels).
        from . import manipulation as _P

        weight = t_(weight)
        shape = [1] * len(label.shape)
        shape[axis % len(label.shape)] = label.shape[axis % len(label.shape)]
        wg = (label * _P.reshape(weight, shape)).sum(axis=axis, keepdim=True)
        if smoothed_ignore_mask is not None:
            keep = 1.0 - smoothed_ignore_mask
            wg = wg * _P.reshape(keep, wg.shape)
        loss = loss * wg
        if reduction == "mean":
            from . import reduction as R

            denom = R.sum(wg)
            # reference guard (loss.py:1839): a fully-padded batch gives
            # weight mass 0 — return 0, never 0/0 = NaN
            denom = denom + (denom == 0).astype(denom.dtype)
            return R.sum(loss) / denom
        return _reduce_loss(loss, reduction)

    if smoothed_ignore_mask is not None:
        # unweighted label_smoothing over hard labels: padding rows must
        # keep contributing ZERO loss and not enter the mean denominator
        # (exactly like the un-smoothed hard-label path below)
        from . import manipulation as _P
        from . import reduction as R

        keep = 1.0 - smoothed_ignore_mask
        loss = loss * _P.reshape(keep, loss.shape)
        if reduction == "mean":
            denom = R.sum(keep)
            denom = denom + (denom == 0).astype(denom.dtype)
            return R.sum(loss) / denom
        return _reduce_loss(loss, reduction)

    if weight is not None:
        weight = t_(weight)
        lbl = label._data if label.ndim < input.ndim else jnp.squeeze(label._data, axis)
        w = Tensor(jnp.take(weight._data, jnp.where(lbl == ignore_index, 0, lbl))[..., None])
        loss = loss * w
        if reduction == "mean":
            from . import reduction as R

            valid = Tensor(jnp.where(lbl == ignore_index, 0.0, 1.0)[..., None])
            return R.sum(loss) / R.sum(w * valid)

    if reduction == "mean" and not soft_label:
        # mean over VALID tokens — labels may contain ignore_index (e.g. the default
        # -100 padding convention); dividing by total N would shrink the loss
        from . import reduction as R

        lbl = label._data if label.ndim < input.ndim else jnp.squeeze(label._data, axis)
        denom = jnp.maximum((lbl != ignore_index).sum(), 1)
        return R.sum(loss) / Tensor(denom.astype(loss._data.dtype))
    return _reduce_loss(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    input, label = t_(input), t_(label)

    def kernel(lp, lb, *w):
        safe = jnp.where(lb == ignore_index, 0, lb)
        picked = -jnp.take_along_axis(lp, safe[..., None] if lp.ndim == lb.ndim + 1 else safe, axis=1 if lp.ndim == 2 else 1)
        picked = jnp.squeeze(picked, 1) if picked.ndim > lb.ndim else picked
        if w:
            picked = picked * jnp.take(w[0], safe)
        return jnp.where(lb == ignore_index, 0.0, picked)

    args = [input, label] + ([t_(weight)] if weight is not None else [])
    loss = apply("nll_loss", kernel, args, nondiff_mask=[False, True] + ([True] if weight is not None else []))
    if reduction == "mean" and weight is not None:
        from . import reduction as R

        lbl = label._data
        w_sum = Tensor(jnp.take(t_(weight)._data, jnp.where(lbl == ignore_index, 0, lbl)) *
                       (lbl != ignore_index))
        return R.sum(loss) / R.sum(w_sum)
    return _reduce_loss(loss, reduction)


def mse_loss(input, label, reduction="mean", name=None):
    loss = apply("mse_loss", lambda a, b: jnp.square(a - b), [t_(input), t_(label)])
    return _reduce_loss(loss, reduction)


def l1_loss(input, label, reduction="mean", name=None):
    loss = apply("l1_loss", lambda a, b: jnp.abs(a - b), [t_(input), t_(label)])
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def kernel(a, b, delta):
        d = jnp.abs(a - b)
        return jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)

    loss = apply("smooth_l1_loss", kernel, [t_(input), t_(label)], {"delta": delta})
    return _reduce_loss(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def kernel(p, l, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(l * jnp.log(p) + (1 - l) * jnp.log1p(-p))
        if w:
            loss = loss * w[0]
        return loss

    args = [t_(input), t_(label)] + ([t_(weight)] if weight is not None else [])
    loss = apply("binary_cross_entropy", kernel, args)
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def kernel(z, l, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        max_val = jnp.clip(-z, 0, None)
        if pw is not None:
            log_w = (pw - 1) * l + 1
            loss = (1 - l) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = jnp.clip(z, 0, None) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        if w is not None:
            loss = loss * w
        return loss

    args = [t_(logit), t_(label)]
    if weight is not None:
        args.append(t_(weight))
    if pos_weight is not None:
        args.append(t_(pos_weight))
    loss = apply("bce_with_logits", kernel, args)
    return _reduce_loss(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):
    def kernel(lp, t):
        return t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp)

    loss = apply("kl_div", kernel, [t_(input), t_(label)])
    if reduction == "batchmean":
        from . import reduction as R

        return R.sum(loss) / t_(input).shape[0]
    return _reduce_loss(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def kernel(z, l):
        p = jax.nn.sigmoid(z)
        ce = jnp.clip(z, 0, None) - z * l + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * l + (1 - p) * (1 - l)
        mod = jnp.power(1 - p_t, gamma)
        a_t = alpha * l + (1 - alpha) * (1 - l)
        return a_t * mod * ce

    loss = apply("sigmoid_focal_loss", kernel, [t_(logit), t_(label)])
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def kernel(a, b, l, margin):
        return jnp.clip(-l * (a - b) + margin, 0, None)

    loss = apply("margin_ranking_loss", kernel, [t_(input), t_(other), t_(label)],
                 {"margin": margin})
    return _reduce_loss(loss, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def kernel(a, b, axis, eps):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)

    return apply("cosine_similarity", kernel, [t_(x1), t_(x2)], {"axis": axis, "eps": eps})


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    sim = cosine_similarity(input1, input2, axis=1)
    label = t_(label)

    def kernel(s, l, margin):
        return jnp.where(l > 0, 1 - s, jnp.clip(s - margin, 0, None))

    loss = apply("cosine_embedding_loss", kernel, [sim, label], {"margin": margin},
                 nondiff_mask=[False, True])
    return _reduce_loss(loss, reduction)


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), [t_(input), t_(label)])


# ---------- attention ----------

def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Inputs [batch, seq, heads, head_dim] (paddle convention)."""
    q, k, v = t_(query), t_(key), t_(value)
    args = [q, k, v]
    if attn_mask is not None:
        args.append(t_(attn_mask))

    # attention-weight dropout (paddle semantics) is only supported by the dense
    # path — with it active, flash/ring must not be used
    attn_dropout = dropout_p if training else 0.0

    # Sequence-parallel: ring attention over the 'sp' mesh axis (SURVEY.md §5.7)
    from ..distributed.meta_parallel import sequence_parallel as _sp

    if attn_mask is None and attn_dropout == 0.0 and _sp.active():
        return _sp.apply_ring_attention(q, k, v, causal=is_causal)

    def kernel(q, k, v, *mask):
        scale = 1.0 / _math.sqrt(q.shape[-1])
        if not mask and attn_dropout == 0.0 and _use_flash(q, k):
            from .pallas import flash_attention as _flash

            return _flash(q, k, v, causal=is_causal, sm_scale=scale)
        qt = jnp.swapaxes(q, 1, 2)  # [b, h, s, d]
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                scores = jnp.where(m, scores, -1e9)
            else:
                scores = scores + m
        if is_causal:
            sq, sk = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((sq, sk), bool))
            scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
        if attn_dropout > 0.0:
            # dropout on the attention WEIGHTS (paddle semantics), not the output
            keep = 1.0 - attn_dropout
            drop_mask = jax.random.bernoulli(drop_key, keep, probs.shape)
            probs = jnp.where(drop_mask, probs / keep, 0.0).astype(probs.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
        return jnp.swapaxes(out, 1, 2)

    drop_key = random_mod.next_key() if attn_dropout > 0.0 else None
    return apply("attention", kernel, args,
                 nondiff_mask=[False, False, False] + ([True] * (len(args) - 3)))


def _flash_flag_allows() -> bool:
    """The flag half of the flash-routing decision, shared by the dense
    route, ring SP, and Ulysses SP so the policies cannot drift: flag ON,
    and off-TPU additionally a DELIBERATE opt-in (use_flash_attention
    explicitly set + pallas_interpret_ok) — or enabling interpret mode for
    another kernel would silently reroute all attention through the
    orders-of-magnitude-slower interpreted kernel.

    Underscore-private to stay OFF the public API surface (API.spec), but
    intentionally imported by distributed/meta_parallel/sequence_parallel —
    renaming/inlining it breaks the ring/Ulysses routing policy; the SP
    parity tests pin that contract."""
    import jax as _jax

    from ..core import flags as _flags
    if not _flags.flag("use_flash_attention"):
        return False
    return _jax.default_backend() == "tpu" or (
        _flags.flag("pallas_interpret_ok")
        and _flags.was_set("use_flash_attention"))


def _use_flash(q, k) -> bool:
    """Route to the Pallas flash kernel: TPU only (interpret mode is test-only),
    long-enough sequences, supported tiling."""
    if not _flash_flag_allows():
        return False
    from .pallas.flash_attention import supported

    sq, sk, d = q.shape[1], k.shape[1], q.shape[-1]
    return sq >= 128 and sk >= 128 and supported(sq, sk, d) and \
        q.dtype in (jnp.float32, jnp.bfloat16)


# ---------- misc ----------

def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    x = t_(x)
    nd = x.ndim - 2
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    spatial_axes = list(range(1, 1 + nd)) if channel_last else list(range(2, 2 + nd))
    in_sizes = [x.shape[a] for a in spatial_axes]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(s) for s in size.numpy().reshape(-1)]
        out_sizes = [int(s) if not isinstance(s, Tensor) else int(s.item()) for s in
                     (size if isinstance(size, (list, tuple)) else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        out_sizes = [int(s * f) for s, f in zip(in_sizes, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]

    def kernel(a):
        shape = list(a.shape)
        for ax, os in zip(spatial_axes, out_sizes):
            shape[ax] = os
        return jax.image.resize(a, shape, method=jmode)

    return apply("interpolate", kernel, [x])


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    def kernel(a, r):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)

    return apply("pixel_shuffle", kernel, [t_(x)], {"r": upscale_factor})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = t_(x)
    ks = _pair(kernel_sizes, 2)
    st = _pair(strides, 2)
    pd = _pair(paddings, 2)
    dl = _pair(dilations, 2)

    def kernel(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        oh = (h + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (w + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        cols = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                patch = a_p[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                            j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
                cols.append(patch)
        out = jnp.stack(cols, 2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], oh * ow)

    return apply("unfold", kernel, [x])


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Row-wise [0, maxlen) < length mask (reference: fluid/layers/sequence_lod.py
    sequence_mask, used by the dynamic rnn runner for state blending)."""
    x = t_(x)
    if maxlen is None:
        if getattr(x, "is_symbolic", False):
            raise ValueError("sequence_mask requires an explicit maxlen when "
                             "building a static program (lengths are symbolic)")
        maxlen = int(np.asarray(x._data).max()) if x._data.size else 0

    def kernel(lens, maxlen, dtype):
        return (jnp.arange(maxlen) < lens[..., None]).astype(dtype)

    return apply("sequence_mask", kernel, [x],
                 {"maxlen": int(maxlen), "dtype": dtypes.convert_dtype(dtype)},
                 differentiable=False)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference: python/paddle/nn/functional/extension.py)."""

    def kernel(a, offset, dim1, dim2):
        n = a.shape[-1] + abs(offset)
        ndim = a.ndim + 1
        d1 = dim1 % ndim
        d2 = dim2 % ndim
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        rows = jnp.arange(a.shape[-1]) + max(-offset, 0)
        cols = jnp.arange(a.shape[-1]) + max(offset, 0)
        base = base.at[..., rows, cols].set(a)
        # base has the two new axes last; move them to (d1, d2)
        order = list(range(a.ndim - 1))
        remaining = [ax for ax in range(ndim) if ax not in (d1, d2)]
        perm = [0] * ndim
        for src, dst in zip(order, remaining):
            perm[dst] = src
        perm[d1] = a.ndim - 1
        perm[d2] = a.ndim
        return jnp.transpose(base, perm)

    return apply("diag_embed", kernel, [t_(input)],
                 {"offset": offset, "dim1": dim1, "dim2": dim2})


# ---------- adaptive pools (1d/3d) + max-pool indices + unpool ----------

def _adaptive_pool_nd(name, x, output_size, nd, reducer):
    """Adaptive pooling over the last nd spatial axes of an NC... tensor."""
    x = t_(x)
    out_sz = _pair(output_size, nd)
    spatial_axes = list(range(2, 2 + nd))
    in_sz = [x.shape[ax] for ax in spatial_axes]
    out_sz = tuple(in_sz[i] if out_sz[i] is None else out_sz[i] for i in range(nd))

    def kernel(a):
        red = jnp.max if reducer == "max" else jnp.mean

        def pool_axis(arr, axis, osz):
            isz = arr.shape[axis]
            starts = (np.arange(osz) * isz) // osz
            ends = ((np.arange(osz) + 1) * isz + osz - 1) // osz
            pieces = [red(jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                          axis=axis, keepdims=True) for s, e in zip(starts, ends)]
            return jnp.concatenate(pieces, axis=axis)

        for ax, osz in zip(spatial_axes, out_sz):
            a = pool_axis(a, ax, osz)
        return a

    return apply(name, kernel, [x])


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd("adaptive_avg_pool3d", x, output_size, 3, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd("adaptive_max_pool1d", x, output_size, 1, "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool_nd("adaptive_max_pool3d", x, output_size, 3, "max")
    return (out, None) if return_mask else out


def _max_pool_with_indices(name, x, kernel_size, stride, padding, nd):
    """Max pool returning (values, flat spatial argmax indices) — the unpool
    contract (reference: max_pool2d_with_index op)."""
    x = t_(x)
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)
    in_sz = [x.shape[2 + i] for i in range(nd)]
    out_sz = [(in_sz[i] + 2 * pd[i] - ks[i]) // st[i] + 1 for i in range(nd)]

    def kernel(a):
        neg = -jnp.inf if dtypes.is_floating(a.dtype) else jnp.iinfo(a.dtype).min
        a_p = jnp.pad(a, [(0, 0), (0, 0)] + [(p, p + k) for p, k in zip(pd, ks)],
                      constant_values=neg)
        patches = []
        offsets = list(np.ndindex(*ks))
        for off in offsets:
            sl = [slice(None), slice(None)]
            for i in range(nd):
                sl.append(slice(off[i], off[i] + out_sz[i] * st[i], st[i]))
            patches.append(a_p[tuple(sl)])
        stacked = jnp.stack(patches, axis=-1)            # [N, C, *out, K]
        vals = jnp.max(stacked, axis=-1)
        karg = jnp.argmax(stacked, axis=-1)              # window-relative
        # window-relative -> absolute unpadded flat index
        off_arr = np.asarray(offsets)                    # [K, nd]
        out_grid = np.meshgrid(*[np.arange(o) for o in out_sz], indexing="ij")
        flat = jnp.zeros(karg.shape, jnp.int64)
        mult = 1
        for i in range(nd - 1, -1, -1):
            abs_i = (jnp.asarray(out_grid[i]) * st[i]
                     + jnp.asarray(off_arr[:, i])[karg] - pd[i])
            flat = flat + abs_i.astype(jnp.int64) * mult
            mult *= in_sz[i]
        return vals, flat

    return apply(name, kernel, [x], nondiff_mask=None)


def _max_unpool_nd(name, x, indices, kernel_size, stride, padding, output_size, nd,
                   data_format):
    x = t_(x)
    indices = t_(indices)
    ks = _pair(kernel_size, nd)
    st = _pair(stride if stride is not None else kernel_size, nd)
    pd = _pair(padding, nd)
    in_sz = [x.shape[2 + i] for i in range(nd)]
    if output_size is None:
        out_sz = [(in_sz[i] - 1) * st[i] - 2 * pd[i] + ks[i] for i in range(nd)]
    else:
        out_sz = list(output_size)[-nd:]

    def kernel(a, idx):
        n, c = a.shape[0], a.shape[1]
        flat_len = int(np.prod(out_sz))
        a_f = a.reshape(n, c, -1)
        i_f = idx.reshape(n, c, -1)
        out = jnp.zeros((n, c, flat_len), a.dtype)
        bi = jnp.arange(n)[:, None, None]
        ci = jnp.arange(c)[None, :, None]
        out = out.at[bi, ci, i_f].set(a_f)
        return out.reshape([n, c] + out_sz)

    return apply(name, kernel, [x, indices])


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
    return _max_unpool_nd("max_unpool1d", x, indices, kernel_size, stride, padding,
                          output_size, 1, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
    return _max_unpool_nd("max_unpool2d", x, indices, kernel_size, stride, padding,
                          output_size, 2, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
    return _max_unpool_nd("max_unpool3d", x, indices, kernel_size, stride, padding,
                          output_size, 3, data_format)


# ---------- extra losses ----------

def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2|X∩Y|/(|X|+|Y|) per batch, meaned (reference nn/functional/loss.py)."""
    input = t_(input)
    label = t_(label)

    def kernel(p, l, epsilon):
        lf = jax.nn.one_hot(l.squeeze(-1), p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * lf, axis=reduce_dims)
        denom = jnp.sum(p, axis=reduce_dims) + jnp.sum(lf, axis=reduce_dims)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (denom + epsilon))

    return apply("dice_loss", kernel, [input, label], {"epsilon": epsilon})


def log_loss(input, label, epsilon=1e-4, name=None):
    def kernel(p, l, epsilon):
        return -l * jnp.log(p + epsilon) - (1.0 - l) * jnp.log(1.0 - p + epsilon)

    return apply("log_loss", kernel, [t_(input), t_(label)], {"epsilon": epsilon})


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (reference nn/functional/loss.py:npair_loss)."""
    anchor, positive, labels = t_(anchor), t_(positive), t_(labels)

    def kernel(a, p, l, l2_reg):
        l = l.reshape(-1, 1).astype(a.dtype)
        same = (l == l.T).astype(a.dtype)
        targets = same / jnp.sum(same, axis=1, keepdims=True)
        sim = a @ p.T
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = jnp.mean(jnp.sum(-targets * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) + jnp.mean(jnp.sum(p * p, 1))) / 2
        return ce + reg

    return apply("npair_loss", kernel, [anchor, positive, labels], {"l2_reg": l2_reg})


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def kernel(x, y, margin):
        loss = jnp.where(y == 1.0, x, jnp.maximum(0.0, margin - x))
        return loss

    out = apply("hinge_embedding_loss", kernel, [t_(input), t_(label)],
                {"margin": margin})
    return _reduce_loss(out, reduction)


def _reduce_loss(out, reduction):
    from . import reduction as R

    if reduction == "mean":
        return R.mean(out)
    if reduction == "sum":
        return R.sum(out)
    return out


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid over a complete binary tree (default) or a custom
    tree given by path_table/path_code (reference: hierarchical_sigmoid op,
    paddle/fluid/operators/hierarchical_sigmoid_op.h MatrixBitCodeFunctor)."""
    input, label, weight = t_(input), t_(label), t_(weight)
    lab_np = np.asarray(label._data).reshape(-1)
    if path_table is None:
        # default complete binary tree: node code = label + num_classes,
        # walk from root; internal node ids are (code >> k) - 1
        codes = [int(c) + num_classes for c in lab_np]
        max_len = max((c.bit_length() - 1 for c in codes), default=0)
        tbl = np.zeros((len(codes), max_len), np.int64)
        cod = np.zeros((len(codes), max_len), np.float32)
        msk = np.zeros((len(codes), max_len), np.float32)
        for r, c in enumerate(codes):
            length = c.bit_length() - 1
            for j in range(length):
                tbl[r, j] = (c >> (length - j)) - 1
                cod[r, j] = float((c >> (length - 1 - j)) & 1)
                msk[r, j] = 1.0
        path_table = Tensor(jnp.asarray(tbl))
        path_code = Tensor(jnp.asarray(cod))
        mask = Tensor(jnp.asarray(msk))
    else:
        path_table, path_code = t_(path_table), t_(path_code)
        mask = Tensor((path_table._data >= 0).astype(jnp.float32))
        path_table = Tensor(jnp.maximum(path_table._data, 0))

    args = [input, weight, path_table, path_code, mask]
    if bias is not None:
        args.append(t_(bias))

    def kernel(x, w, tbl, cod, msk, *maybe_b):
        w_path = w[tbl]                       # [N, L, D]
        pre = jnp.einsum("nld,nd->nl", w_path, x)
        if maybe_b:
            pre = pre + maybe_b[0].reshape(-1)[tbl]
        # BCE-with-logits against the path code bits, masked to real path length
        loss = jnp.maximum(pre, 0) - pre * cod + jnp.log1p(jnp.exp(-jnp.abs(pre)))
        return jnp.mean(jnp.sum(loss * msk, axis=1))

    return apply("hsigmoid_loss", kernel, args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace-family margin softmax on cosine logits (reference:
    operators/margin_cross_entropy_op.cu; model-parallel grouping handled by
    the caller's mp layers here)."""
    logits, label = t_(logits), t_(label)

    def kernel(cosv, l, margin1, margin2, margin3, scale):
        lab = l.reshape(-1)
        onehot = jax.nn.one_hot(lab, cosv.shape[-1], dtype=cosv.dtype)
        theta = jnp.arccos(jnp.clip(cosv, -1.0 + 1e-7, 1.0 - 1e-7))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adjusted = onehot * target + (1.0 - onehot) * cosv
        z = adjusted * scale
        logp = jax.nn.log_softmax(z, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
        return loss, jax.nn.softmax(z, axis=-1)

    loss, soft = apply("margin_cross_entropy", kernel, [logits, label],
                       {"margin1": margin1, "margin2": margin2,
                        "margin3": margin3, "scale": scale})
    loss = _reduce_loss(loss, reduction)
    if return_softmax:
        return loss, soft
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss via the forward algorithm as one lax.scan over time
    (reference: warpctc op, operators/warpctc_op.cc; TPU-native instead of the
    external warp-ctc kernel). log_probs: [T, N, C] logits (softmax applied
    internally, like the reference)."""
    log_probs, labels = t_(log_probs), t_(labels)
    input_lengths, label_lengths = t_(input_lengths), t_(label_lengths)

    def kernel(logits, lab, in_len, lab_len, blank):
        lp = jax.nn.log_softmax(logits, axis=-1)      # [T, N, C]
        T, N, C = lp.shape
        L = lab.shape[1]
        S = 2 * L + 1
        NEG = -1e30
        # extended label sequence: blank, l1, blank, l2, ..., blank
        ext = jnp.full((N, S), blank, lab.dtype)
        ext = ext.at[:, 1::2].set(lab)
        # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
        ext_prev2 = jnp.concatenate([jnp.full((N, 2), -1, ext.dtype), ext[:, :-2]], 1)
        can_skip = (ext != blank) & (ext != ext_prev2)

        alpha0 = jnp.full((N, S), NEG)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(N), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, lp[0, jnp.arange(N), ext[:, 1]], NEG))

        def step(alpha, t):
            prev1 = jnp.concatenate([jnp.full((N, 1), NEG), alpha[:, :-1]], 1)
            prev2 = jnp.concatenate([jnp.full((N, 2), NEG), alpha[:, :-2]], 1)
            prev2 = jnp.where(can_skip, prev2, NEG)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            emit = jnp.take_along_axis(lp[t], ext, axis=1)   # [N, S]
            new = merged + emit
            # freeze rows whose time is up
            live = (t < in_len)[:, None]
            return jnp.where(live, new, alpha), None

        alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        s_last = 2 * lab_len  # index of final blank
        a_last = jnp.take_along_axis(alphaT, s_last[:, None], 1)[:, 0]
        a_prev = jnp.where(
            lab_len > 0,
            jnp.take_along_axis(alphaT, jnp.maximum(s_last - 1, 0)[:, None], 1)[:, 0],
            NEG)
        nll = -jnp.logaddexp(a_last, a_prev)
        if norm_by_times:
            nll = nll / in_len.astype(nll.dtype)
        return nll

    out = apply("ctc_loss", kernel, [log_probs, labels, input_lengths, label_lengths],
                {"blank": blank})
    return _reduce_loss(out, reduction)


# ---------- spatial / vision ops ----------

def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2-D affine sampling grid (reference: affine_grid op)."""
    theta = t_(theta)
    n, _, h, w = [int(s) for s in out_shape]

    def kernel(th, h, w, align_corners):
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1.0
            xs = (jnp.arange(w) * 2 + 1) / w - 1.0
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
        return jnp.einsum("hwk,nck->nhwc", base, th)            # [N, H, W, 2]

    return apply("affine_grid", kernel, [theta],
                 {"h": h, "w": w, "align_corners": align_corners})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True,
                name=None):
    """Bilinear/nearest sampling of NCHW by an [N,H,W,2] grid in [-1,1]
    (reference: grid_sampler op)."""
    x, grid = t_(x), t_(grid)

    def kernel(a, g, mode, padding_mode, align_corners):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]

        def unnormalize(coord, size):
            if align_corners:
                return (coord + 1.0) / 2.0 * (size - 1)
            return ((coord + 1.0) * size - 1.0) / 2.0

        fx = unnormalize(gx, w)
        fy = unnormalize(gy, h)

        def get(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            v = a[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [N, Hg, Wg, C]
            if padding_mode == "zeros":
                inside = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
                v = v * inside[..., None].astype(v.dtype)
            return v

        if mode == "nearest":
            out = get(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = fx - x0
            wy = fy - y0
            v00, v01 = get(x0, y0), get(x1, y0)
            v10, v11 = get(x0, y1), get(x1, y1)
            wx = wx[..., None]
            wy = wy[..., None]
            out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                   + v10 * (1 - wx) * wy + v11 * wx * wy)
        return jnp.transpose(out, (0, 3, 1, 2))  # NHWC -> NCHW

    return apply("grid_sample", kernel, [x, grid],
                 {"mode": mode, "padding_mode": padding_mode,
                  "align_corners": align_corners})


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """Shift a fraction of channels one step along the segment (time) dim
    (reference: temporal_shift op)."""
    x = t_(x)

    def kernel(a, seg_num, shift_ratio):
        nt, c, h, w = a.shape
        n = nt // seg_num
        a = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(a[:, :1, fold:2 * fold]),
                                 a[:, :-1, fold:2 * fold]], 1)
        rest = a[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], axis=2)
        return out.reshape(nt, c, h, w)

    return apply("temporal_shift", kernel, [x],
                 {"seg_num": seg_num, "shift_ratio": shift_ratio})


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[n,k] = x1[n,:] @ W[k] @ x2[n,:] + b (reference: bilinear_tensor_product)."""
    args = [t_(x1), t_(x2), t_(weight)] + ([t_(bias)] if bias is not None else [])

    def kernel(a, b, w, *maybe_bias):
        out = jnp.einsum("ni,kij,nj->nk", a, w, b)
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    return apply("bilinear", kernel, args)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    x = t_(x)
    p = _pair(padding, 4)  # left, right, top, bottom

    def kernel(a, p, channel_last):
        if channel_last:
            pads = [(0, 0), (p[2], p[3]), (p[0], p[1]), (0, 0)]
        else:
            pads = [(0, 0), (0, 0), (p[2], p[3]), (p[0], p[1])]
        return jnp.pad(a, pads)

    return apply("zeropad2d", kernel, [x],
                 {"p": tuple(int(v) for v in p),
                  "channel_last": data_format == "NHWC"})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """Inverse of unfold: scatter-add columns back into the image
    (reference: fold op)."""
    x = t_(x)
    out_hw = _pair(output_sizes, 2)
    ks = _pair(kernel_sizes, 2)
    st = _pair(strides, 2)
    pd = _pair(paddings, 2)
    dl = _pair(dilations, 2)

    def kernel(a):
        n, ckk, ol = a.shape
        c = ckk // (ks[0] * ks[1])
        oh = (out_hw[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (out_hw[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        a = a.reshape(n, c, ks[0], ks[1], oh, ow)
        hp, wp = out_hw[0] + 2 * pd[0], out_hw[1] + 2 * pd[1]
        out = jnp.zeros((n, c, hp, wp), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                             j * dl[1]: j * dl[1] + ow * st[1]: st[1]].add(a[:, :, i, j])
        return out[:, :, pd[0]: hp - pd[0], pd[1]: wp - pd[1]]

    return apply("fold", kernel, [x])


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers + all positives; remap labels
    (reference: class_center_sample op). Host-side sampling, eager only."""
    label = t_(label)
    lab = np.asarray(label._data).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        rng = np.random.default_rng(random_mod.default_generator().initial_seed())
        extra = rng.choice(rest, size=num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])), Tensor(jnp.asarray(sampled)))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention via a dense mask built from the CSR pattern.
    The reference ships a CUDA-only kernel (operators/sparse_attention_op.cu);
    on TPU the XLA/Pallas flash path (ops/pallas) covers the perf case, so this
    provides semantics, not the sparse kernel."""
    q, k, v = t_(query), t_(key), t_(value)
    offs, cols = t_(sparse_csr_offset), t_(sparse_csr_columns)

    def kernel(q, k, v, offs, cols):
        b, h, T, d = q.shape
        mask = jnp.zeros((b, h, T, T), bool)
        offs_np = offs
        for r in range(T):
            # rows share the CSR layout per (batch, head)
            start = offs_np[..., r]
            end = offs_np[..., r + 1]
            idx = jnp.arange(cols.shape[-1])
            sel = (idx >= start[..., None]) & (idx < end[..., None])
            row_cols = jnp.where(sel, cols, -1)
            row_mask = jnp.zeros((b, h, T), bool)
            row_mask = row_mask.at[
                jnp.arange(b)[:, None, None], jnp.arange(h)[None, :, None],
                row_cols].set(True)
            row_mask = row_mask & (row_cols >= 0).any(-1)[..., None]
            mask = mask.at[:, :, r, :].set(row_mask)
        scores = jnp.einsum("bhtd,bhsd->bhts", q, k) / jnp.sqrt(d).astype(q.dtype)
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhts,bhsd->bhtd", probs, v)

    return apply("sparse_attention", kernel, [q, k, v, offs, cols])

"""Fused LM-head + softmax-cross-entropy (chunked, recompute-in-backward).

The reference fuses the vocab-parallel loss on GPU as a custom CUDA op
(`paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu`); the
single-chip hot path there still materializes the [tokens, vocab] logits.
On TPU the logits tensor is the single largest activation of a GPT step
(batch 8 x seq 1024 x vocab 50304 in f32 = 1.6 GB, plus autodiff residuals of
the same size), so this op computes

    loss[i] = logsumexp(h[i] @ W) - (h[i] @ W)[label[i]]

in row chunks under `lax.scan`: each chunk's logits live only for the duration
of one scan step, and the backward pass recomputes them chunk-by-chunk instead
of saving softmax residuals (FlashAttention-style recompute applied to the
classifier). Matmul inputs stay in the activation dtype (bf16 under amp) with
f32 accumulation on the MXU; the dW accumulator is carried in f32.

Saved residuals: per-row logsumexp only ([tokens] f32).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ._helpers import t_

_CHUNK = 2048  # rows per scan step: chunk x vocab f32 logits = ~400 MB transient @ 50k vocab


def _logits_chunk(hc, w, transpose_y):
    """[C, H] x W -> [C, V] f32 (W cast to the activation dtype for MXU rate)."""
    wc = w.astype(hc.dtype) if hc.dtype != w.dtype else w
    dims = (((1,), (1,)), ((), ())) if transpose_y else (((1,), (0,)), ((), ()))
    return jax.lax.dot_general(hc, wc, dims, preferred_element_type=jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_lce(h2, w, labels, transpose_y, chunk, ignore_index):
    loss, _ = _lce_fwd_impl(h2, w, labels, transpose_y, chunk, ignore_index)
    return loss


def _lce_fwd_impl(h2, w, labels, transpose_y, chunk, ignore_index):
    n, _ = h2.shape
    nc = n // chunk
    h3 = h2.reshape(nc, chunk, h2.shape[1])
    l3 = labels.reshape(nc, chunk)

    def one(_, hl):
        hc, lc = hl
        logits = _logits_chunk(hc, w, transpose_y)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        safe = jnp.where(lc == ignore_index, 0, lc)
        picked = jnp.take_along_axis(logits, safe[:, None], axis=-1)[:, 0]
        loss = jnp.where(lc == ignore_index, 0.0, lse - picked)
        return None, (loss, lse)

    _, (loss, lse) = jax.lax.scan(one, None, (h3, l3))
    return loss.reshape(n), lse.reshape(n)


def _lce_fwd_rule(h2, w, labels, transpose_y, chunk, ignore_index):
    loss, lse = _lce_fwd_impl(h2, w, labels, transpose_y, chunk, ignore_index)
    return loss, (h2, w, labels, lse)


def _lce_bwd_rule(transpose_y, chunk, ignore_index, res, g):
    h2, w, labels, lse = res
    n, hdim = h2.shape
    v = w.shape[0] if transpose_y else w.shape[1]
    nc = n // chunk
    h3 = h2.reshape(nc, chunk, hdim)
    l3 = labels.reshape(nc, chunk)
    lse3 = lse.reshape(nc, chunk)
    g3 = g.reshape(nc, chunk)

    def one(dw_acc, inp):
        hc, lc, lsec, gc = inp
        logits = _logits_chunk(hc, w, transpose_y)          # recompute, [C, V] f32
        p = jnp.exp(logits - lsec[:, None])
        safe = jnp.where(lc == ignore_index, 0, lc)
        onehot = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1) == safe[:, None].astype(jnp.int32)
        gc = jnp.where(lc == ignore_index, 0.0, gc)
        dl = ((p - onehot) * gc[:, None]).astype(hc.dtype)  # [C, V]
        wc = w.astype(hc.dtype) if hc.dtype != w.dtype else w
        if transpose_y:  # W [V, H]
            dh = jax.lax.dot_general(dl, wc, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dw = jax.lax.dot_general(dl, hc, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        else:  # W [H, V]
            dh = jax.lax.dot_general(dl, wc, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            dw = jax.lax.dot_general(hc, dl, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        return dw_acc + dw, dh.astype(hc.dtype)

    dw_shape = (v, hdim) if transpose_y else (hdim, v)
    dw, dh3 = jax.lax.scan(one, jnp.zeros(dw_shape, jnp.float32),
                           (h3, l3, lse3, g3))
    dlabels = np.zeros(labels.shape, dtype=jax.dtypes.float0)
    return dh3.reshape(n, hdim), dw.astype(w.dtype), dlabels


_fused_lce.defvjp(_lce_fwd_rule, _lce_bwd_rule)


def fused_linear_cross_entropy(hidden, weight, label, transpose_y=True,
                               ignore_index=-100, name=None):
    """Per-position LM loss without materializing full logits.

    hidden: [..., H]; weight: [V, H] if transpose_y (tied-embedding layout) else
    [H, V]; label: int [...]. Returns f32 loss of shape [...] (0 where
    label == ignore_index). Chunked over rows; rows are padded with
    ignore_index up to a chunk multiple, so any token count works.
    """
    hidden, weight, label = t_(hidden), t_(weight), t_(label)
    lead_shape = hidden.shape[:-1]
    hdim = hidden.shape[-1]

    def kernel(h, w, lb):
        n = int(np.prod(lead_shape)) if lead_shape else 1
        h2 = h.reshape(n, hdim)
        lb1 = lb.reshape(n).astype(jnp.int32)

        # The online Pallas lm_loss kernel is RETIRED from this path
        # (BASELINE.md round 5: its bench-vocab Mosaic compile exceeded
        # 9.5 min and wedged the chip tunnel twice; the chunked scan below
        # measures 91 TFLOP/s on chip — at the chip's achievable matmul
        # ceiling, leaving the kernel no headroom to win). It remains a
        # direct-call library kernel (ops/pallas/lm_loss.py) with its math
        # pinned by tests/test_pallas_lm_loss.py.
        from ..core.flags import flag as _flag

        cfg_chunk = int(_flag("fused_ce_chunk") or _CHUNK)
        if cfg_chunk < 1:
            raise ValueError(
                f"FLAGS_fused_ce_chunk must be >= 1, got {cfg_chunk}")
        chunk = min(cfg_chunk, n)
        pad = (-n) % chunk
        if pad:
            h2 = jnp.concatenate([h2, jnp.zeros((pad, hdim), h2.dtype)], axis=0)
            lb1 = jnp.concatenate(
                [lb1, jnp.full((pad,), ignore_index, jnp.int32)], axis=0)
        loss = _fused_lce(h2, w, lb1, transpose_y, chunk, ignore_index)
        if pad:
            loss = loss[:n]
        return loss.reshape(lead_shape)

    return apply("fused_linear_cross_entropy", kernel, [hidden, weight, label],
                 nondiff_mask=[False, False, True])

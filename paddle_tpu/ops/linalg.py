"""Linear algebra ops — the MXU path. Reference: python/paddle/tensor/linalg.py +
phi matmul kernels (paddle/phi/kernels/gpu/matmul_kernel.cu). matmuls run in the
flag-selected precision so the MXU is used for f32 inputs unless 'highest' is set."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.flags import flag
from ..core.tensor import Tensor
from ._helpers import t_


def _prec():
    return {"default": None, "high": "bfloat16_3x", "highest": "float32"}.get(
        flag("tpu_matmul_precision"), None)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def kernel(a, b, transpose_x, transpose_y):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b, precision=_prec())

    return apply("matmul", kernel, [t_(x), t_(y)],
                 {"transpose_x": transpose_x, "transpose_y": transpose_y})


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply("bmm", lambda a, b: jnp.matmul(a, b, precision=_prec()), [t_(x), t_(y)])


def mv(x, vec, name=None):
    return apply("mv", lambda a, v: jnp.matmul(a, v, precision=_prec()), [t_(x), t_(vec)])


def dot(x, y, name=None):
    def kernel(a, b):
        return jnp.sum(a * b, axis=-1)

    return apply("dot", kernel, [t_(x), t_(y)])


def einsum(equation, *operands):
    tensors = [t_(o) for o in operands]
    return apply("einsum", lambda *arrays, equation: jnp.einsum(equation, *arrays, precision=_prec()),
                 tensors, {"equation": equation})


def norm(x, p=None, axis=None, keepdim=False, name=None):
    if p is None:
        p = "fro" if axis is None or isinstance(axis, (list, tuple)) else 2

    def kernel(a, p, axis, keepdim):
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis, keepdims=keepdim))
        if p == np.inf:
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == -np.inf:
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=keepdim), 1.0 / p)

    return apply("norm", kernel, [t_(x)], {"p": p, "axis": axis, "keepdim": keepdim})


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p, axis, keepdim)


def dist(x, y, p=2, name=None):
    return norm(x - y if isinstance(x, Tensor) else t_(x) - t_(y), p)


def cross(x, y, axis=9, name=None):
    def kernel(a, b, axis):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)

    return apply("cross", kernel, [t_(x), t_(y)], {"axis": axis})


def cholesky(x, upper=False, name=None):
    def kernel(a, upper):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return apply("cholesky", kernel, [t_(x)], {"upper": upper})


def inverse(x, name=None):
    return apply("inverse", jnp.linalg.inv, [t_(x)])


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a, rcond: jnp.linalg.pinv(a, rtol=rcond), [t_(x)], {"rcond": rcond})


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, [t_(x), t_(y)])


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def kernel(a, b, upper, transpose, unitriangular):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)

    return apply("triangular_solve", kernel, [t_(x), t_(y)],
                 {"upper": upper, "transpose": transpose, "unitriangular": unitriangular})


def qr(x, mode="reduced", name=None):
    q, r = jnp.linalg.qr(t_(x)._data, mode=mode)
    return Tensor(q), Tensor(r)


def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(t_(x)._data, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2).conj())


def eig(x, name=None):
    w, v = np.linalg.eig(np.asarray(t_(x)._data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(t_(x)._data, UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    w = np.linalg.eigvals(np.asarray(t_(x)._data))
    return Tensor(jnp.asarray(w))


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(t_(x)._data, UPLO=UPLO))


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a, n: jnp.linalg.matrix_power(a, n), [t_(x)], {"n": n})


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(t_(x)._data, rtol=tol))


def slogdet(x, name=None):
    sign, logabsdet = jnp.linalg.slogdet(t_(x)._data)
    return Tensor(jnp.stack([sign, logabsdet]))


def det(x, name=None):
    return apply("det", jnp.linalg.det, [t_(x)])


def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(t_(x)._data)
    outs = [Tensor(lu_), Tensor((piv + 1).astype(jnp.int32))]
    if get_infos:
        outs.append(Tensor(jnp.zeros((), jnp.int32)))
    return tuple(outs)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(t_(x)._data, t_(y)._data, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return Tensor(jnp.cov(t_(x)._data, rowvar=rowvar, ddof=1 if ddof else 0))


def corrcoef(x, rowvar=True, name=None):
    return Tensor(jnp.corrcoef(t_(x)._data, rowvar=rowvar))


def histogram(input, bins=100, min=0, max=0, name=None):
    a = np.asarray(t_(input)._data)
    if min == 0 and max == 0:
        min, max = float(a.min()), float(a.max())
    hist, _ = np.histogram(a, bins=bins, range=(min, max))
    return Tensor(jnp.asarray(hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    w = t_(weights)._data if weights is not None else None
    return Tensor(jnp.bincount(t_(x)._data, weights=w, minlength=minlength,
                               length=None))


def multi_dot(x, name=None):
    return apply("multi_dot", lambda *ms: jnp.linalg.multi_dot(ms),
                 [t_(m) for m in x])


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A @ out = x given y = Cholesky factor of A."""

    def kernel(b, f, upper):
        lower = not upper
        z = jax.lax.linalg.triangular_solve(
            f, b, left_side=True, lower=lower, transpose_a=upper)
        return jax.lax.linalg.triangular_solve(
            f, z, left_side=True, lower=lower, transpose_a=lower)

    return apply("cholesky_solve", kernel, [t_(x), t_(y)], {"upper": upper})


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Split combined LU data + pivots (as produced by `lu`) into P, L, U."""
    a = t_(x)._data
    m, n = a.shape[-2], a.shape[-1]
    k = min(m, n)
    L = U = P = None
    if unpack_ludata:
        L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[..., :k, :])
    if unpack_pivots:
        piv = t_(y)._data.astype(jnp.int32) - 1  # sequential row swaps, 1-based
        perm = jnp.broadcast_to(jnp.arange(m, dtype=jnp.int32),
                                piv.shape[:-1] + (m,))

        for i in range(piv.shape[-1]):
            j = piv[..., i]                                      # [...,]
            pi = perm[..., i]                                    # [...,]
            pj = jnp.take_along_axis(perm, j[..., None], -1)[..., 0]
            perm = jnp.where(jnp.arange(m) == i,
                             pj[..., None] if pj.ndim else pj, perm)
            perm = jnp.where(jnp.arange(m) == j[..., None],
                             pi[..., None] if pi.ndim else pi, perm)
        P = (perm[..., :, None] == jnp.arange(m)).astype(a.dtype)
        P = jnp.swapaxes(P, -1, -2)
    outs = []
    if unpack_pivots:
        outs.append(Tensor(P))
    if unpack_ludata:
        outs.extend([Tensor(L), Tensor(U)])
    return tuple(outs)


def cond(x, p=None, name=None):
    return Tensor(jnp.linalg.cond(t_(x)._data, p=p))


inv = inverse  # paddle.linalg.inv alias

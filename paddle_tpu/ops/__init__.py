"""Op namespace assembly + Tensor method attachment.

The reference generates the Tensor method table (`core.eager.ops.*`,
paddle/fluid/pybind/eager_method.cc + generated python_c functions). Here the same wiring is done
by attaching the functional ops to `Tensor` at import time.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from ..core import dtype as _dtypes

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .activation import (  # noqa: F401
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, rrelu, selu, silu, softmax, softplus, softshrink, softsign, swiglu,
    swish, tanhshrink, thresholded_relu,
)
from . import nn_functional as F  # noqa: F401

from . import creation as _creation
from . import math as _math_ops
from . import reduction as _reduction
from . import manipulation as _manip
from . import linalg as _linalg
from . import activation as _activation


def _attach_methods():
    import builtins

    M = _math_ops
    R = _reduction
    P = _manip
    L = _linalg

    def m(name, fn):
        setattr(Tensor, name, fn)

    # arithmetic dunders
    m("__add__", lambda s, o: M.add(s, o))
    m("__radd__", lambda s, o: M.add(o, s))
    m("__sub__", lambda s, o: M.subtract(s, o))
    m("__rsub__", lambda s, o: M.subtract(o, s))
    m("__mul__", lambda s, o: M.multiply(s, o))
    m("__rmul__", lambda s, o: M.multiply(o, s))
    m("__truediv__", lambda s, o: M.divide(s, o))
    m("__rtruediv__", lambda s, o: M.divide(o, s))
    m("__floordiv__", lambda s, o: M.floor_divide(s, o))
    m("__rfloordiv__", lambda s, o: M.floor_divide(o, s))
    m("__mod__", lambda s, o: M.remainder(s, o))
    m("__rmod__", lambda s, o: M.remainder(o, s))
    m("__pow__", lambda s, o: M.pow(s, o))
    m("__rpow__", lambda s, o: M.pow(o, s))
    m("__neg__", lambda s: M.neg(s))
    m("__abs__", lambda s: M.abs(s))
    m("__matmul__", lambda s, o: L.matmul(s, o))
    m("__rmatmul__", lambda s, o: L.matmul(o, s))
    m("__eq__", lambda s, o: M.equal(s, o))
    m("__ne__", lambda s, o: M.not_equal(s, o))
    m("__lt__", lambda s, o: M.less_than(s, o))
    m("__le__", lambda s, o: M.less_equal(s, o))
    m("__gt__", lambda s, o: M.greater_than(s, o))
    m("__ge__", lambda s, o: M.greater_equal(s, o))
    m("__invert__", lambda s: M.logical_not(s) if s.dtype == _dtypes.bool_ else M.bitwise_not(s))
    m("__and__", lambda s, o: M.logical_and(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_and(s, o))
    m("__or__", lambda s, o: M.logical_or(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_or(s, o))
    m("__xor__", lambda s, o: M.logical_xor(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_xor(s, o))
    Tensor.__hash__ = lambda s: id(s)

    # indexing
    m("__getitem__", lambda s, item: P.getitem(s, item))
    m("__setitem__", lambda s, item, v: P.setitem(s, item, v))

    # method-style ops (subset of the generated method table; extend freely)
    method_table = {
        "add": M.add, "subtract": M.subtract, "multiply": M.multiply,
        "divide": M.divide, "pow": M.pow, "matmul": L.matmul, "mm": L.mm,
        "bmm": L.bmm, "dot": L.dot, "maximum": M.maximum, "minimum": M.minimum,
        "abs": M.abs, "exp": M.exp, "log": M.log, "log2": M.log2, "sqrt": M.sqrt,
        "rsqrt": M.rsqrt, "square": M.square, "reciprocal": M.reciprocal,
        "sin": M.sin, "cos": M.cos, "tan": M.tan, "tanh": M.tanh, "erf": M.erf,
        "sigmoid": M.sigmoid, "floor": M.floor, "ceil": M.ceil, "round": M.round,
        "trunc": M.trunc, "sign": M.sign, "clip": M.clip, "neg": M.neg,
        "isnan": M.isnan, "isinf": M.isinf, "isfinite": M.isfinite,
        "equal": M.equal, "not_equal": M.not_equal, "less_than": M.less_than,
        "less_equal": M.less_equal, "greater_than": M.greater_than,
        "greater_equal": M.greater_equal, "logical_and": M.logical_and,
        "logical_or": M.logical_or, "logical_not": M.logical_not,
        "logical_xor": M.logical_xor, "allclose": M.allclose, "isclose": M.isclose,
        "equal_all": M.equal_all, "scale": M.scale, "lerp": M.lerp,
        "cumsum": M.cumsum, "cumprod": M.cumprod, "trace": M.trace,
        "remainder": M.remainder, "mod": M.mod, "floor_divide": M.floor_divide,
        "kron": M.kron, "inner": M.inner, "outer": M.outer, "atan2": M.atan2,
        # reductions
        "sum": R.sum, "mean": R.mean, "max": R.max, "min": R.min, "prod": R.prod,
        "all": R.all, "any": R.any, "argmax": R.argmax, "argmin": R.argmin,
        "std": R.std, "var": R.var, "logsumexp": R.logsumexp, "median": R.median,
        "quantile": R.quantile, "count_nonzero": R.count_nonzero,
        "nansum": R.nansum, "nanmean": R.nanmean, "kthvalue": R.kthvalue,
        # manipulation
        "reshape": P.reshape, "reshape_": P.reshape_, "flatten": P.flatten,
        "transpose": P.transpose, "t": P.t, "moveaxis": P.moveaxis,
        "swapaxes": P.swapaxes, "squeeze": P.squeeze, "unsqueeze": P.unsqueeze,
        "expand": P.expand, "expand_as": P.expand_as, "broadcast_to": P.broadcast_to,
        "tile": P.tile, "flip": P.flip, "roll": P.roll, "gather": P.gather,
        "gather_nd": P.gather_nd, "scatter": P.scatter,
        "scatter_nd_add": P.scatter_nd_add, "index_select": P.index_select,
        "index_sample": P.index_sample, "index_add": P.index_add,
        "masked_select": P.masked_select, "masked_fill": P.masked_fill,
        "take_along_axis": P.take_along_axis, "put_along_axis": P.put_along_axis,
        "sort": P.sort, "argsort": P.argsort, "topk": P.topk, "unique": P.unique,
        "nonzero": P.nonzero, "where": P.where, "split": P.split, "chunk": P.chunk,
        "unbind": P.unbind, "cast": P.cast, "astype": P.astype,
        "repeat_interleave": P.repeat_interleave, "diff": P.diff,
        "strided_slice": P.strided_slice, "slice": P.slice,
        # linalg
        "norm": L.norm, "dist": L.dist, "cross": L.cross, "cholesky": L.cholesky,
        "inverse": L.inverse, "pinv": L.pinv, "matrix_power": L.matrix_power,
        "det": L.det, "slogdet": L.slogdet, "histogram": L.histogram,
        "bincount": L.bincount, "cov": L.cov, "corrcoef": L.corrcoef,
        # activations
        "softmax": _activation.softmax, "log_softmax": _activation.log_softmax,
        "relu": _activation.relu, "gelu": _activation.gelu,
        # creation-like
        "tril": _creation.tril, "triu": _creation.triu, "diag": _creation.diag,
    }
    import jax.numpy as _jnp

    method_table["fill_"] = lambda s, v: s._replace_data(_jnp.full_like(s._data, v))
    method_table["zero_"] = lambda s: s._replace_data(_jnp.zeros_like(s._data))
    for name, fn in method_table.items():
        m(name, fn)

    # in-place arithmetic helpers (dygraph surface; used under no_grad by optimizers)
    from .manipulation import _inplace_rebind

    def _inplace(opname, fn):
        def impl(s, *a, **k):
            return _inplace_rebind(s, fn, *a, **k)

        m(opname, impl)

    _inplace("add_", M.add)
    _inplace("subtract_", M.subtract)
    _inplace("multiply_", M.multiply)
    _inplace("divide_", M.divide)
    _inplace("scale_", M.scale)
    _inplace("clip_", M.clip)
    _inplace("exp_", M.exp)
    _inplace("sqrt_", M.sqrt)
    _inplace("abs_", M.abs)
    _inplace("tanh_", M.tanh)
    _inplace("relu_", _activation.relu)
    _inplace("flatten_", P.flatten)
    _inplace("squeeze_", P.squeeze)
    _inplace("unsqueeze_", P.unsqueeze)


_attach_methods()

"""Op namespace assembly + Tensor method attachment.

The reference generates the Tensor method table (`core.eager.ops.*`,
paddle/fluid/pybind/eager_method.cc + generated python_c functions). Here the same wiring is done
by attaching the functional ops to `Tensor` at import time.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from ..core import dtype as _dtypes

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .attribute import *  # noqa: F401,F403
from .activation import (  # noqa: F401
    celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid, hardswish,
    hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout, mish, prelu, relu,
    relu6, rrelu, selu, silu, softmax, softplus, softshrink, softsign, swiglu,
    swish, tanhshrink, thresholded_relu,
)
from . import nn_functional as F  # noqa: F401

from . import creation as _creation
from . import math as _math_ops
from . import reduction as _reduction
from . import manipulation as _manip
from . import linalg as _linalg
from . import activation as _activation
from . import attribute as _attribute


def _attach_methods():
    import builtins

    M = _math_ops
    R = _reduction
    P = _manip
    L = _linalg

    def m(name, fn):
        setattr(Tensor, name, fn)

    # arithmetic dunders
    m("__add__", lambda s, o: M.add(s, o))
    m("__radd__", lambda s, o: M.add(o, s))
    m("__sub__", lambda s, o: M.subtract(s, o))
    m("__rsub__", lambda s, o: M.subtract(o, s))
    m("__mul__", lambda s, o: M.multiply(s, o))
    m("__rmul__", lambda s, o: M.multiply(o, s))
    m("__truediv__", lambda s, o: M.divide(s, o))
    m("__rtruediv__", lambda s, o: M.divide(o, s))
    m("__floordiv__", lambda s, o: M.floor_divide(s, o))
    m("__rfloordiv__", lambda s, o: M.floor_divide(o, s))
    m("__mod__", lambda s, o: M.remainder(s, o))
    m("__rmod__", lambda s, o: M.remainder(o, s))
    m("__pow__", lambda s, o: M.pow(s, o))
    m("__rpow__", lambda s, o: M.pow(o, s))
    m("__neg__", lambda s: M.neg(s))
    m("__abs__", lambda s: M.abs(s))
    m("__matmul__", lambda s, o: L.matmul(s, o))
    m("__rmatmul__", lambda s, o: L.matmul(o, s))
    m("__eq__", lambda s, o: M.equal(s, o))
    m("__ne__", lambda s, o: M.not_equal(s, o))
    m("__lt__", lambda s, o: M.less_than(s, o))
    m("__le__", lambda s, o: M.less_equal(s, o))
    m("__gt__", lambda s, o: M.greater_than(s, o))
    m("__ge__", lambda s, o: M.greater_equal(s, o))
    m("__invert__", lambda s: M.logical_not(s) if s.dtype == _dtypes.bool_ else M.bitwise_not(s))
    m("__and__", lambda s, o: M.logical_and(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_and(s, o))
    m("__or__", lambda s, o: M.logical_or(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_or(s, o))
    m("__xor__", lambda s, o: M.logical_xor(s, o) if s.dtype == _dtypes.bool_ else M.bitwise_xor(s, o))
    Tensor.__hash__ = lambda s: id(s)

    # indexing
    m("__getitem__", lambda s, item: P.getitem(s, item))
    m("__setitem__", lambda s, item, v: P.setitem(s, item, v))

    # method-style ops (subset of the generated method table; extend freely)
    method_table = {
        "add": M.add, "subtract": M.subtract, "multiply": M.multiply,
        "divide": M.divide, "pow": M.pow, "matmul": L.matmul, "mm": L.mm,
        "bmm": L.bmm, "dot": L.dot, "maximum": M.maximum, "minimum": M.minimum,
        "abs": M.abs, "exp": M.exp, "log": M.log, "log2": M.log2, "sqrt": M.sqrt,
        "rsqrt": M.rsqrt, "square": M.square, "reciprocal": M.reciprocal,
        "sin": M.sin, "cos": M.cos, "tan": M.tan, "tanh": M.tanh, "erf": M.erf,
        "sigmoid": M.sigmoid, "floor": M.floor, "ceil": M.ceil, "round": M.round,
        "trunc": M.trunc, "sign": M.sign, "clip": M.clip, "neg": M.neg,
        "isnan": M.isnan, "isinf": M.isinf, "isfinite": M.isfinite,
        "equal": M.equal, "not_equal": M.not_equal, "less_than": M.less_than,
        "less_equal": M.less_equal, "greater_than": M.greater_than,
        "greater_equal": M.greater_equal, "logical_and": M.logical_and,
        "logical_or": M.logical_or, "logical_not": M.logical_not,
        "logical_xor": M.logical_xor, "allclose": M.allclose, "isclose": M.isclose,
        "equal_all": M.equal_all, "scale": M.scale, "lerp": M.lerp,
        "cumsum": M.cumsum, "cumprod": M.cumprod, "trace": M.trace,
        "remainder": M.remainder, "mod": M.mod, "floor_divide": M.floor_divide,
        "kron": M.kron, "inner": M.inner, "outer": M.outer, "atan2": M.atan2,
        # reductions
        "sum": R.sum, "mean": R.mean, "max": R.max, "min": R.min, "prod": R.prod,
        "all": R.all, "any": R.any, "argmax": R.argmax, "argmin": R.argmin,
        "std": R.std, "var": R.var, "logsumexp": R.logsumexp, "median": R.median,
        "quantile": R.quantile, "count_nonzero": R.count_nonzero,
        "nansum": R.nansum, "nanmean": R.nanmean, "kthvalue": R.kthvalue,
        # manipulation
        "reshape": P.reshape, "reshape_": P.reshape_, "flatten": P.flatten,
        "transpose": P.transpose, "t": P.t, "moveaxis": P.moveaxis,
        "swapaxes": P.swapaxes, "squeeze": P.squeeze, "unsqueeze": P.unsqueeze,
        "expand": P.expand, "expand_as": P.expand_as, "broadcast_to": P.broadcast_to,
        "tile": P.tile, "flip": P.flip, "roll": P.roll, "gather": P.gather,
        "gather_nd": P.gather_nd, "scatter": P.scatter,
        "scatter_nd_add": P.scatter_nd_add, "index_select": P.index_select,
        "index_sample": P.index_sample, "index_add": P.index_add,
        "masked_select": P.masked_select, "masked_fill": P.masked_fill,
        "take_along_axis": P.take_along_axis, "put_along_axis": P.put_along_axis,
        "sort": P.sort, "argsort": P.argsort, "topk": P.topk, "unique": P.unique,
        "nonzero": P.nonzero, "where": P.where, "split": P.split, "chunk": P.chunk,
        "unbind": P.unbind, "cast": P.cast, "astype": P.astype,
        "repeat_interleave": P.repeat_interleave, "diff": P.diff,
        "strided_slice": P.strided_slice, "slice": P.slice,
        # linalg
        "norm": L.norm, "dist": L.dist, "cross": L.cross, "cholesky": L.cholesky,
        "inverse": L.inverse, "pinv": L.pinv, "matrix_power": L.matrix_power,
        "det": L.det, "slogdet": L.slogdet, "histogram": L.histogram,
        "bincount": L.bincount, "cov": L.cov, "corrcoef": L.corrcoef,
        # activations
        "softmax": _activation.softmax, "log_softmax": _activation.log_softmax,
        "relu": _activation.relu, "gelu": _activation.gelu,
        # creation-like
        "tril": _creation.tril, "triu": _creation.triu, "diag": _creation.diag,
        "numel": _creation.numel, "diag_embed": _creation.diag_embed,
        "fill_diagonal_tensor": _creation.fill_diagonal_tensor,
        # more unary math
        "acos": M.acos, "asin": M.asin, "atan": M.atan, "sinh": M.sinh,
        "cosh": M.cosh, "asinh": M.asinh, "acosh": M.acosh, "atanh": M.atanh,
        "log10": M.log10, "log1p": M.log1p, "expm1": M.expm1, "logit": M.logit,
        "lgamma": M.lgamma, "digamma": M.digamma, "erfinv": M.erfinv,
        "frac": M.frac, "conj": M.conj, "real": M.real, "imag": M.imag,
        "angle": M.angle, "rad2deg": M.rad2deg, "deg2rad": M.deg2rad,
        "stanh": M.stanh, "increment": M.increment, "multiplex": M.multiplex,
        "nan_to_num": M.nan_to_num, "sgn": M.sgn, "i0": M.i0,
        "cummax": M.cummax, "cummin": M.cummin, "logcumsumexp": M.logcumsumexp,
        "diagonal": M.diagonal, "addmm": M.addmm, "renorm": M.renorm,
        "add_n": M.add_n, "heaviside": M.heaviside, "hypot": M.hypot,
        "copysign": M.copysign, "nextafter": M.nextafter, "ldexp": M.ldexp,
        "logaddexp": M.logaddexp,
        # more binary math
        "fmax": M.fmax, "fmin": M.fmin, "floor_mod": M.floor_mod,
        "gcd": M.gcd, "lcm": M.lcm,
        "bitwise_and": M.bitwise_and, "bitwise_or": M.bitwise_or,
        "bitwise_xor": M.bitwise_xor, "bitwise_not": M.bitwise_not,
        # more reductions
        "amax": R.amax, "amin": R.amin, "nanmedian": R.nanmedian,
        "nanquantile": R.nanquantile, "mode": R.mode,
        # attributes
        "rank": _attribute.rank, "is_empty": _attribute.is_empty,
        "is_complex": _attribute.is_complex, "is_integer": _attribute.is_integer,
        "is_floating_point": _attribute.is_floating_point,
        # more manipulation
        "concat": P.concat, "stack": P.stack, "unstack": P.unstack,
        "reverse": P.reverse, "rot90": P.rot90, "tensordot": P.tensordot,
        "unique_consecutive": P.unique_consecutive, "as_real": P.as_real,
        "as_complex": P.as_complex, "shard_index": P.shard_index,
        "searchsorted": P.searchsorted, "bucketize": P.bucketize,
        "broadcast_tensors": P.broadcast_tensors, "index_put": P.index_put,
        "view": P.view,
        # more linalg
        "mv": L.mv, "qr": L.qr, "svd": L.svd, "eig": L.eig, "eigh": L.eigh,
        "eigvals": L.eigvals, "eigvalsh": L.eigvalsh, "lstsq": L.lstsq,
        "cond": L.cond, "lu": L.lu, "lu_unpack": L.lu_unpack,
        "multi_dot": L.multi_dot, "solve": L.solve,
        "cholesky_solve": L.cholesky_solve,
        "triangular_solve": L.triangular_solve, "matrix_rank": L.matrix_rank,
    }
    import jax.numpy as _jnp

    method_table["fill_"] = lambda s, v: s._replace_data(_jnp.full_like(s._data, v))
    method_table["zero_"] = lambda s: s._replace_data(_jnp.zeros_like(s._data))
    for name, fn in method_table.items():
        m(name, fn)

    # in-place arithmetic helpers (dygraph surface; used under no_grad by optimizers)
    from .manipulation import _inplace_rebind

    def _inplace(opname, fn):
        def impl(s, *a, **k):
            return _inplace_rebind(s, fn, *a, **k)

        m(opname, impl)

    _inplace("add_", M.add)
    _inplace("subtract_", M.subtract)
    _inplace("multiply_", M.multiply)
    _inplace("divide_", M.divide)
    _inplace("scale_", M.scale)
    _inplace("clip_", M.clip)
    _inplace("exp_", M.exp)
    _inplace("sqrt_", M.sqrt)
    _inplace("abs_", M.abs)
    _inplace("tanh_", M.tanh)
    _inplace("relu_", _activation.relu)
    _inplace("flatten_", P.flatten)
    _inplace("squeeze_", P.squeeze)
    _inplace("unsqueeze_", P.unsqueeze)
    _inplace("ceil_", M.ceil)
    _inplace("floor_", M.floor)
    _inplace("round_", M.round)
    _inplace("reciprocal_", M.reciprocal)
    _inplace("rsqrt_", M.rsqrt)
    _inplace("lerp_", M.lerp)
    _inplace("erfinv_", M.erfinv)
    _inplace("scatter_", P.scatter)
    _inplace("put_along_axis_", P.put_along_axis)

    def _uniform_(s, min=-1.0, max=1.0, seed=0):
        from ..core import random as _random
        import jax as _jax

        key = _random.next_key()
        s.set_value(_jax.random.uniform(key, s._data.shape, s._data.dtype,
                                        minval=min, maxval=max))
        return s

    def _exponential_(s, lam=1.0):
        from ..core import random as _random
        import jax as _jax

        key = _random.next_key()
        u = _jax.random.uniform(key, s._data.shape, dtype=s._data.dtype)
        s.set_value(-_jnp.log1p(-u) / lam)
        return s

    def _normal_(s, mean=0.0, std=1.0):
        from ..core import random as _random
        import jax as _jax

        key = _random.next_key()
        s.set_value(mean + std * _jax.random.normal(key, s._data.shape, s._data.dtype))
        return s

    def _fill_diagonal_(s, value, offset=0, wrap=False):
        # exact reference semantics (fill_diagonal_op.cc:102-118): walk FLAT
        # positions i = k * stride where stride = sum_d prod(dims[d+1:])
        # (nc+1 for 2-D), capped at dims[1]^2 when wrap is off, and write at
        # i + offset only while the offset stays inside i's row
        # (0 <= i % dims[1] + offset < dims[1]).
        import numpy as _np

        a = s._data
        dims = a.shape
        if a.ndim > 2 and len(set(dims)) != 1:
            raise ValueError(
                "fill_diagonal_: tensors with ndim > 2 must have all "
                f"dimensions equal, got {list(dims)}")
        stride = 0
        prod = 1
        for d in range(a.ndim - 1, -1, -1):
            stride += prod
            prod *= dims[d]
        size = a.size
        if not wrap and a.ndim == 2:
            # deliberate deviation for ndim > 2: the reference applies this
            # dims[1]^2 cap to cubes too, where stride > dims[1]^2 leaves
            # only element (0,..,0) filled — a kernel bug; torch (and any
            # sane reading) fills the whole space diagonal, as we do
            size = size if size < dims[1] * dims[1] else dims[1] * dims[1]
        i = _np.arange(0, size, stride)
        col = i % dims[1] + offset
        i = i[(col >= 0) & (col < dims[1])]
        flat = a.reshape(-1).at[i + offset].set(_jnp.asarray(value, a.dtype))
        s.set_value(flat.reshape(dims))
        return s

    def _fill_diagonal_tensor_(s, y, offset=0, dim1=0, dim2=1):
        s.set_value(_creation.fill_diagonal_tensor(
            s, y, offset=offset, dim1=dim1, dim2=dim2)._data)
        return s

    m("uniform_", _uniform_)
    m("exponential_", _exponential_)
    m("normal_", _normal_)
    m("fill_diagonal_", _fill_diagonal_)
    m("fill_diagonal_tensor_", _fill_diagonal_tensor_)
    m("fill_diagonal_tensor", _creation.fill_diagonal_tensor)
    m("diag_embed", _creation.diag_embed)

    # module-level functions the reference also binds onto Tensor even though
    # their first argument is not a tensor (python/paddle/tensor/__init__.py)
    Tensor.broadcast_shape = staticmethod(P.broadcast_shape)
    Tensor.scatter_nd = staticmethod(P.scatter_nd)
    Tensor.is_tensor = staticmethod(_attribute.is_tensor)


_attach_methods()

"""Shape / layout / indexing ops.

Reference parity: python/paddle/tensor/manipulation.py (+ phi reshape/transpose/concat/... kernels).
Paddle-specific semantics preserved: `transpose(x, perm)` takes a full permutation; `gather`
selects rows by a 1-D index along `axis`; `scatter` overwrite/add by row index.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.dispatch import apply, as_tensor
from ..core.tensor import Tensor
from ._helpers import normalize_axis, t_


def _static_shape(shape):
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(s.item()))
        else:
            out.append(int(s))
    return tuple(out)


def cast(x, dtype):
    d = dtypes.convert_dtype(dtype)
    x = t_(x)
    if x.dtype == d:
        return x
    return apply("cast", lambda a, d: a.astype(d), [x], {"d": d},
                 differentiable=dtypes.is_floating(d) and dtypes.is_floating(x.dtype))


astype = cast


def reshape(x, shape, name=None):
    return apply("reshape", lambda a, shape: jnp.reshape(a, shape), [t_(x)],
                 {"shape": _static_shape(shape)})


def _inplace_rebind(x, op, *args, **kwargs):
    """Run `op` out-of-place on a snapshot of x's autograd identity, then graft the
    result back onto x. The snapshot (not x itself) becomes the grad node's input, so
    the graph stays acyclic. Matches torch/paddle semantics: in-place on a leaf that
    requires grad (outside no_grad) is an error."""
    from ..core.autograd import is_grad_enabled

    if is_grad_enabled() and not x.stop_gradient and x._node is None:
        raise RuntimeError(
            "a leaf Tensor that requires grad is being used in an in-place operation; "
            "wrap in paddle.no_grad() or operate on a non-leaf result")
    snap = Tensor(x._data, stop_gradient=x._stop_gradient)
    snap._node, snap._out_index = x._node, x._out_index
    out = op(snap, *args, **kwargs)
    x._data, x._node, x._out_index = out._data, out._node, out._out_index
    if not out.stop_gradient:
        x._stop_gradient = False
    return x


def reshape_(x, shape, name=None):
    return _inplace_rebind(x, reshape, shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = t_(x)
    nd = builtins.max(x.ndim, 1)
    sa = normalize_axis(start_axis, nd)
    ea = normalize_axis(stop_axis, nd)
    shp = x.shape
    new_shape = tuple(shp[:sa]) + (-1,) + tuple(shp[ea + 1:])
    return reshape(x, new_shape)


def transpose(x, perm, name=None):
    return apply("transpose", lambda a, perm: jnp.transpose(a, perm), [t_(x)],
                 {"perm": tuple(int(p) for p in perm)})


def t(x, name=None):
    x = t_(x)
    if x.ndim < 2:
        return x
    return transpose(x, list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda a, s, d: jnp.moveaxis(a, s, d), [t_(x)],
                 {"s": source, "d": destination})


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda a, x0, x1: jnp.swapaxes(a, x0, x1), [t_(x)],
                 {"x0": axis0, "x1": axis1})


def concat(x, axis=0, name=None):
    tensors = [t_(a) for a in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    kernel = lambda *arrays, axis: jnp.concatenate(arrays, axis=axis)
    return apply("concat", kernel, tensors, {"axis": int(axis)})


def stack(x, axis=0, name=None):
    tensors = [t_(a) for a in x]
    kernel = lambda *arrays, axis: jnp.stack(arrays, axis=axis)
    return apply("stack", kernel, tensors, {"axis": int(axis)})


def vstack(x):
    return apply("vstack", lambda *a: jnp.vstack(a), [t_(a) for a in x])


def hstack(x):
    return apply("hstack", lambda *a: jnp.hstack(a), [t_(a) for a in x])


def dstack(x):
    return apply("dstack", lambda *a: jnp.dstack(a), [t_(a) for a in x])


def split(x, num_or_sections, axis=0, name=None):
    x = t_(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = normalize_axis(axis, x.ndim)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} along axis {axis} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item()) for s in num_or_sections]
        n_unknown = builtins.sum(1 for s in sizes if s == -1)
        if n_unknown:
            known = builtins.sum(s for s in sizes if s != -1)
            sizes = [s if s != -1 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    def kernel(a, offsets, sizes, axis):
        return tuple(jax.lax.slice_in_dim(a, o, o + s, axis=axis) for o, s in zip(offsets, sizes))

    outs = apply("split", kernel, [x], {"offsets": offsets, "sizes": sizes, "axis": axis})
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = t_(x)
    axis = normalize_axis(axis, x.ndim)
    n = x.shape[axis]

    def kernel(a, axis, n):
        return tuple(jnp.squeeze(jax.lax.slice_in_dim(a, i, i + 1, axis=axis), axis) for i in range(n))

    return list(apply("unbind", kernel, [x], {"axis": axis, "n": n}))


def squeeze(x, axis=None, name=None):
    x = t_(x)
    if axis is None:
        ax = None
    else:
        if isinstance(axis, (int, np.integer)):
            axis = [axis]
        ax = tuple(a for a in (normalize_axis(tuple(axis), x.ndim)) if x.shape[a] == 1)
    return apply("squeeze", lambda a, axis: jnp.squeeze(a, axis=axis), [x], {"axis": ax})


def unsqueeze(x, axis, name=None):
    x = t_(x)
    if isinstance(axis, Tensor):
        axis = [int(a) for a in axis.numpy().reshape(-1)]
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    return apply("unsqueeze", lambda a, axis: jnp.expand_dims(a, axis=axis), [x],
                 {"axis": tuple(axis)})


def expand(x, shape, name=None):
    x = t_(x)
    shape = _static_shape(shape)
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s == -1 else s for i, s in enumerate(shape))
    return apply("expand", lambda a, shape: jnp.broadcast_to(a, shape), [x], {"shape": shape})


broadcast_to = expand


def expand_as(x, y, name=None):
    return expand(x, t_(y).shape)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(inputs, name=None):
    arrays = jnp.broadcast_arrays(*[t_(i)._data for i in inputs])
    return [Tensor(a) for a in arrays]


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = [int(r) for r in repeat_times.numpy().reshape(-1)]
    return apply("tile", lambda a, reps: jnp.tile(a, reps), [t_(x)],
                 {"reps": tuple(int(r) if not isinstance(r, Tensor) else int(r.item()) for r in repeat_times)})


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats.numpy())
        return apply("repeat_interleave", lambda a, reps, axis: jnp.repeat(a, jnp.asarray(reps), axis=axis),
                     [t_(x)], {"reps": tuple(reps.tolist()), "axis": axis})
    return apply("repeat_interleave", lambda a, reps, axis: jnp.repeat(a, reps, axis=axis),
                 [t_(x)], {"reps": int(repeats), "axis": axis})


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return apply("flip", lambda a, axis: jnp.flip(a, axis=axis), [t_(x)], {"axis": tuple(axis)})


reverse = flip


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda a, k, axes: jnp.rot90(a, k, axes), [t_(x)], {"k": k, "axes": tuple(axes)})


def roll(x, shifts, axis=None, name=None):
    return apply("roll", lambda a, shifts, axis: jnp.roll(a, shifts, axis=axis), [t_(x)],
                 {"shifts": shifts, "axis": axis})


def where(condition, x=None, y=None, name=None):
    condition = t_(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where", lambda c, a, b: jnp.where(c, a, b),
                 [condition, as_tensor(x), as_tensor(y)],
                 nondiff_mask=[True, False, False])


def nonzero(x, as_tuple=False, name=None):
    data = np.asarray(t_(x)._data)  # dynamic shape -> host (matches reference sync semantics)
    nz = np.nonzero(data)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_select(x, mask, name=None):
    x, mask = t_(x), t_(mask)
    # host sync for the dynamic output shape; the gather stays differentiable
    m = np.asarray(jnp.broadcast_to(mask._data, x._data.shape))
    flat_idx = jnp.asarray(np.nonzero(m.reshape(-1))[0])
    return apply("masked_select", lambda a: a.reshape(-1)[flat_idx], [x])


def masked_fill(x, mask, value, name=None):
    if isinstance(value, Tensor):
        return apply("masked_fill", lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                     [t_(x), t_(mask), value], nondiff_mask=[False, True, False])
    return apply("masked_fill", lambda a, m, value: jnp.where(m, value, a),
                 [t_(x), t_(mask)], {"value": value}, nondiff_mask=[False, True])


def gather(x, index, axis=0, name=None):
    """Paddle gather: select slices along axis by a 1-D (or 0-d) index."""
    x, index = t_(x), t_(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    return apply("gather", lambda a, i, axis: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=axis),
                 [x, index], {"axis": axis}, nondiff_mask=[False, True])


def gather_nd(x, index, name=None):
    x, index = t_(x), t_(index)

    def kernel(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]

    return apply("gather_nd", kernel, [x, index], nondiff_mask=[False, True])


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply("take_along_axis", lambda a, i, axis: jnp.take_along_axis(a, i, axis=axis),
                 [t_(arr), t_(indices)], {"axis": axis}, nondiff_mask=[False, True])


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices = t_(arr), t_(indices)
    values = as_tensor(values)

    def kernel(a, i, v, axis, reduce):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = list(range(a.ndim))
        idx = []
        for d in dims:
            if d == axis:
                idx.append(i)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d]
                base = jnp.arange(a.shape[d]).reshape(shape)
                idx.append(jnp.broadcast_to(base, i.shape))
        idx = tuple(idx)
        if reduce == "assign":
            return a.at[idx].set(v)
        if reduce == "add":
            return a.at[idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[idx].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")

    return apply("put_along_axis", kernel, [arr, indices, values],
                 {"axis": axis, "reduce": reduce}, nondiff_mask=[False, True, False])


def scatter(x, index, updates, overwrite=True, name=None):
    """Paddle scatter: rows of x at `index` replaced (or accumulated) with `updates`."""
    x, index, updates = t_(x), t_(index), t_(updates)

    def kernel(a, i, u, overwrite):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u.astype(a.dtype))
        # paddle semantics: zero out target rows then add (handles dup indices by sum)
        zeroed = a.at[i].set(jnp.zeros_like(u, a.dtype))
        return zeroed.at[i].add(u.astype(a.dtype))

    return apply("scatter", kernel, [x, index, updates], {"overwrite": bool(overwrite)},
                 nondiff_mask=[False, True, False])


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = t_(x), t_(index), t_(updates)

    def kernel(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u.astype(a.dtype))

    return apply("scatter_nd_add", kernel, [x, index, updates], nondiff_mask=[False, True, False])


def scatter_nd(index, updates, shape, name=None):
    index, updates = t_(index), t_(updates)
    zeros = Tensor(jnp.zeros(_static_shape(shape), updates._data.dtype))
    return scatter_nd_add(zeros, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    return take_along_axis(x, index, axis=1)


def index_add(x, index, axis, value, name=None):
    x, index, value = t_(x), t_(index), t_(value)

    def kernel(a, i, v, axis):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v.astype(a.dtype), axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)

    return apply("index_add", kernel, [x, index, value], {"axis": axis},
                 nondiff_mask=[False, True, False])


def index_put(x, indices, value, accumulate=False, name=None):
    x = t_(x)
    value = as_tensor(value)
    idx = tuple(t_(i)._data for i in indices)

    def kernel(a, v, accumulate):
        if accumulate:
            return a.at[idx].add(v.astype(a.dtype))
        return a.at[idx].set(v.astype(a.dtype))

    return apply("index_put", kernel, [x, value], {"accumulate": accumulate})


def sort(x, axis=-1, descending=False, name=None):
    def kernel(a, axis, descending):
        out = jnp.sort(a, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out

    return apply("sort", kernel, [t_(x)], {"axis": axis, "descending": descending})


def argsort(x, axis=-1, descending=False, name=None):
    def kernel(a, axis, descending):
        out = jnp.argsort(a, axis=axis)
        return (jnp.flip(out, axis=axis) if descending else out).astype(jnp.int64)

    return apply("argsort", kernel, [t_(x)], {"axis": axis, "descending": descending},
                 differentiable=False)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = t_(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    axis = normalize_axis(axis if axis is not None else -1, x.ndim)

    def kernel(a, k, axis, largest):
        a_m = jnp.moveaxis(a, axis, -1)
        if largest:
            vals, inds = jax.lax.top_k(a_m, k)
        else:
            vals, inds = jax.lax.top_k(-a_m, k)
            vals = -vals
        return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(inds.astype(jnp.int64), -1, axis)

    vals, inds = apply("topk", kernel, [x], {"k": k, "axis": axis, "largest": largest})
    return vals, inds


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    data = np.asarray(t_(x)._data)
    res = np.unique(data, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    # paddle returns (out, index?, inverse?, counts?)
    return tuple(outs) if len(outs) > 1 else outs[0]


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64",
                       name=None):
    data = np.asarray(t_(x)._data)
    if axis is None:
        data = data.reshape(-1)
        ax = 0
    else:
        ax = axis
    changed = np.ones(data.shape[ax], bool)
    if data.shape[ax] > 1:
        sl = [slice(None)] * data.ndim
        sl2 = [slice(None)] * data.ndim
        sl[ax], sl2[ax] = slice(1, None), slice(None, -1)
        diff = (np.take(data, range(1, data.shape[ax]), ax) != np.take(data, range(0, data.shape[ax] - 1), ax))
        while diff.ndim > 1:
            diff = diff.any(axis=-1 if ax == 0 else 0)
        changed[1:] = diff
    keep = np.nonzero(changed)[0]
    out = [Tensor(jnp.asarray(np.take(data, keep, ax)))]
    if return_inverse:
        inv = np.cumsum(changed) - 1
        out.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        counts = np.diff(np.append(keep, data.shape[ax]))
        out.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return tuple(out) if len(out) > 1 else out[0]


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def kernel(s, v, right):
        side = "right" if right else "left"
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side)
        return jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(s, v)

    out = apply("searchsorted", kernel, [t_(sorted_sequence), t_(values)], {"right": right},
                differentiable=False)
    return cast(out, "int32" if out_int32 else "int64")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = t_(x)
    if isinstance(pad, Tensor):
        pad = [int(p) for p in pad.numpy().reshape(-1)]
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        width = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle NCHW/NCL/NCDHW convention: pad applies to spatial dims, last-dim-first
        n_spatial = len(pad) // 2
        width = [(0, 0)] * nd
        if data_format.startswith("NC"):
            spatial = list(range(2, 2 + n_spatial))
        else:
            spatial = list(range(1, 1 + n_spatial))
        for j, d in enumerate(reversed(spatial)):
            width[d] = (pad[2 * j], pad[2 * j + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]

    def kernel(a, width, jmode, value):
        if jmode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return apply("pad", kernel, [x], {"width": tuple(width), "jmode": jmode, "value": value})


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = t_(x)

    def kernel(a, axes, starts, ends, strides):
        # builtins.slice: the module-level `slice` op shadows the builtin here
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(s, e, st)
        return a[tuple(idx)]

    return apply("strided_slice", kernel, [x],
                 {"axes": tuple(axes), "starts": tuple(starts), "ends": tuple(ends),
                  "strides": tuple(strides)})


def slice(x, axes, starts, ends, name=None):
    return strided_slice(x, axes, starts, ends, [1] * len(axes))


def crop(x, shape=None, offsets=None, name=None):
    x = t_(x)
    shape = _static_shape(shape)
    offsets = [0] * x.ndim if offsets is None else [int(o) for o in offsets]
    return strided_slice(x, list(range(x.ndim)), offsets,
                         [o + s for o, s in zip(offsets, shape)])


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def kernel(a, index_num, nshards, shard_id, ignore_value):
        shard_size = (index_num + nshards - 1) // nshards
        in_shard = (a // shard_size) == shard_id
        return jnp.where(in_shard, a % shard_size, ignore_value)

    return apply("shard_index", kernel, [t_(input)],
                 {"index_num": index_num, "nshards": nshards, "shard_id": shard_id,
                  "ignore_value": ignore_value}, differentiable=False)


def tensordot(x, y, axes=2, name=None):
    return apply("tensordot", lambda a, b, axes: jnp.tensordot(a, b, axes), [t_(x), t_(y)],
                 {"axes": axes})


def as_real(x, name=None):
    return apply("as_real", lambda a: jnp.stack([a.real, a.imag], -1), [t_(x)])


def as_complex(x, name=None):
    return apply("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), [t_(x)])


def unstack(x, axis=0, num=None, name=None):
    x = t_(x)
    n = x._data.shape[axis] if num is None else num
    assert n == x._data.shape[axis], "num must equal the size of axis"
    return unbind(x, axis)


def reverse(x, axis, name=None):
    return flip(x, axis)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def atleast_1d(*inputs):
    outs = [Tensor(jnp.atleast_1d(t_(i)._data)) for i in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_2d(*inputs):
    outs = [Tensor(jnp.atleast_2d(t_(i)._data)) for i in inputs]
    return outs if len(outs) > 1 else outs[0]


def atleast_3d(*inputs):
    outs = [Tensor(jnp.atleast_3d(t_(i)._data)) for i in inputs]
    return outs if len(outs) > 1 else outs[0]


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    tensors = [t_(x)]
    def kernel(a, n, axis):
        return jnp.diff(a, n=n, axis=axis)
    return apply("diff", kernel, tensors, {"n": n, "axis": axis})


# ---- __getitem__ / __setitem__ machinery ----

def _convert_index(item):
    """Convert a python index expression (possibly containing Tensors) to jnp form."""
    if isinstance(item, tuple):
        return tuple(_convert_index(i) for i in item)
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, (list, np.ndarray)):
        return jnp.asarray(np.asarray(item))
    return item  # int, slice, None, Ellipsis


def _index_has_bool(idx):
    if isinstance(idx, tuple):
        return builtins.any(_index_has_bool(i) for i in idx)
    return (hasattr(idx, "dtype") and idx.dtype == np.bool_) or isinstance(idx, bool)


def getitem(x, item):
    x = t_(x)
    idx = _convert_index(item)
    if _index_has_bool(idx):
        # Dynamic-shape path: the mask is materialized on host (the reference's bool
        # index also forces a D2H sync), converted to integer indices so the gather
        # itself stays on-device and DIFFERENTIABLE.
        def to_int(i):
            if hasattr(i, "dtype") and i.dtype == np.bool_:
                nz = np.nonzero(np.asarray(i))
                return tuple(jnp.asarray(z) for z in nz) if len(nz) > 1 else jnp.asarray(nz[0])
            return i

        if isinstance(idx, tuple):
            new_idx = []
            for i in idx:
                c = to_int(i)
                if isinstance(c, tuple):
                    new_idx.extend(c)
                else:
                    new_idx.append(c)
            idx = tuple(new_idx)
        else:
            idx = to_int(idx)

    def kernel(a):
        return a[idx]

    return apply("getitem", kernel, [x])


def setitem(x, item, value):
    idx = _convert_index(item)
    value = as_tensor(value)

    def kernel(a, v):
        return a.at[idx].set(v.astype(a.dtype))

    return _inplace_rebind(x, lambda snap, v: apply("setitem", kernel, [snap, v]), value)

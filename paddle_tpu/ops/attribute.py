"""Tensor attribute ops.

Reference parity: python/paddle/tensor/attribute.py (shape/rank/is_* helpers) — there these
lower to C++ ops (`shape`, `rank`) or dtype checks on VarType; here dtype queries go through
jnp dtypes (bfloat16-aware) and shape/rank return device tensors like the reference does.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import t_


def is_tensor(x):
    return isinstance(x, Tensor)


def rank(input, name=None):
    return Tensor(jnp.asarray(t_(input).ndim, dtype=jnp.int32))


def shape(input, name=None):
    return Tensor(jnp.asarray(t_(input)._data.shape, dtype=jnp.int32))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(t_(x)._data.size == 0))


def is_complex(x):
    return bool(jnp.issubdtype(t_(x)._data.dtype, jnp.complexfloating))


def is_integer(x):
    return bool(jnp.issubdtype(t_(x)._data.dtype, jnp.integer))


def is_floating_point(x):
    return bool(jnp.issubdtype(t_(x)._data.dtype, jnp.floating))


def check_shape(shape):
    """Validate a shape argument (reference: fluid/layers/utils.py:373)."""
    if isinstance(shape, Tensor):
        return
    for ele in shape:
        if not isinstance(ele, Tensor):
            if ele < 0:
                raise ValueError(
                    "All elements in shape must be positive when argument shape is a list or tuple")
            if not isinstance(ele, (int, np.integer)):
                raise TypeError("Elements in shape must be integers or Tensors")

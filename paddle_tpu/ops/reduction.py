"""Reduction ops. Reference parity: python/paddle/tensor/math.py reduce_* + stat.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.dispatch import apply
from ..core.tensor import Tensor
from ._helpers import normalize_axis, t_


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("sum", lambda a, axis, keepdim, dtype: jnp.sum(a, axis=axis, keepdims=keepdim, dtype=dtype),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim), "dtype": d})


def mean(x, axis=None, keepdim=False, name=None):
    return apply("mean", lambda a, axis, keepdim: jnp.mean(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def max(x, axis=None, keepdim=False, name=None):
    return apply("max", lambda a, axis, keepdim: jnp.max(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def min(x, axis=None, keepdim=False, name=None):
    return apply("min", lambda a, axis, keepdim: jnp.min(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("prod", lambda a, axis, keepdim, dtype: jnp.prod(a, axis=axis, keepdims=keepdim, dtype=dtype),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim), "dtype": d})


def all(x, axis=None, keepdim=False, name=None):
    return apply("all", lambda a, axis, keepdim: jnp.all(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)}, differentiable=False)


def any(x, axis=None, keepdim=False, name=None):
    return apply("any", lambda a, axis, keepdim: jnp.any(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)}, differentiable=False)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    return apply("argmax", lambda a, axis, keepdim: jnp.argmax(
        a, axis=axis, keepdims=keepdim if axis is not None else False).astype(d),
        [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)}, differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    return apply("argmin", lambda a, axis, keepdim: jnp.argmin(
        a, axis=axis, keepdims=keepdim if axis is not None else False).astype(d),
        [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)}, differentiable=False)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std", lambda a, axis, keepdim, ddof: jnp.std(a, axis=axis, keepdims=keepdim, ddof=ddof),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim), "ddof": 1 if unbiased else 0})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var", lambda a, axis, keepdim, ddof: jnp.var(a, axis=axis, keepdims=keepdim, ddof=ddof),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim), "ddof": 1 if unbiased else 0})


def logsumexp(x, axis=None, keepdim=False, name=None):
    import jax.scipy.special as jss

    return apply("logsumexp", lambda a, axis, keepdim: jss.logsumexp(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def k(a, axis, keepdim):
        if mode == "min":
            n = a.shape[axis] if axis is not None else a.size
            srt = jnp.sort(a.reshape(-1) if axis is None else a, axis=0 if axis is None else axis)
            return jnp.take(srt, (n - 1) // 2, axis=0 if axis is None else axis)
        return jnp.median(a, axis=axis, keepdims=keepdim)

    return apply("median", k, [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply("nanmedian", lambda a, axis, keepdim: jnp.nanmedian(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("nansum", lambda a, axis, keepdim, dtype: jnp.nansum(a, axis=axis, keepdims=keepdim, dtype=dtype),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim), "dtype": d})


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", lambda a, axis, keepdim: jnp.nanmean(a, axis=axis, keepdims=keepdim),
                 [t_(x)], {"axis": _axis(axis), "keepdim": bool(keepdim)})


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply("count_nonzero", lambda a, axis, keepdim: jnp.count_nonzero(
        a, axis=axis, keepdims=keepdim).astype(jnp.int64), [t_(x)],
        {"axis": _axis(axis), "keepdim": bool(keepdim)}, differentiable=False)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply("quantile", lambda a, q, axis, keepdim, method: jnp.quantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim, method=method), [t_(x)],
        {"q": q, "axis": _axis(axis), "keepdim": bool(keepdim), "method": interpolation})


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply("nanquantile", lambda a, q, axis, keepdim: jnp.nanquantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim), [t_(x)],
        {"q": q, "axis": _axis(axis), "keepdim": bool(keepdim)})


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = t_(x)
    ax = normalize_axis(axis, x.ndim)
    inds = jnp.take(jnp.argsort(x._data, axis=ax), k - 1, axis=ax)
    # values gathered through the differentiable take_along_axis op so the
    # tape records the kthvalue grad (scatter into the selected slot) —
    # reference kthvalue_grad (backward.yaml)
    from .manipulation import squeeze, take_along_axis

    tv = take_along_axis(x, Tensor(jnp.expand_dims(inds, ax)), ax)
    ti = jnp.expand_dims(inds, ax).astype(jnp.int64)
    if keepdim:
        return tv, Tensor(ti)
    return squeeze(tv, ax), Tensor(jnp.squeeze(ti, ax))


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value (and an index of it) along `axis`.

    Reference: python/paddle/tensor/search.py mode + mode_op; tie semantics per
    the reference numpy oracle (test_mode_op.py:_mode1D): among equally
    frequent values the smallest wins, and the returned index is the original
    position of that value's last occurrence.

    TPU-first: fully vectorized — stable sort along the axis, segmented
    run-length count via a cumulative max of run-start positions, then a
    single argmax over run-end frequencies (first-max tie-breaking lands on
    the smallest value because the axis is sorted ascending).
    """
    x = t_(x)
    ax = normalize_axis(axis, x.ndim)
    data = jnp.moveaxis(x._data, ax, -1)
    n = data.shape[-1]
    order = jnp.argsort(data, axis=-1, stable=True)
    svals = jnp.take_along_axis(data, order, axis=-1)

    pos = jnp.arange(n)
    is_start = jnp.concatenate(
        [jnp.ones(data.shape[:-1] + (1,), bool),
         svals[..., 1:] != svals[..., :-1]], axis=-1)
    last_start = jax.lax.cummax(jnp.where(is_start, pos, 0), axis=data.ndim - 1)
    run_len = pos - last_start + 1
    is_end = jnp.concatenate(
        [svals[..., 1:] != svals[..., :-1],
         jnp.ones(data.shape[:-1] + (1,), bool)], axis=-1)
    freq = jnp.where(is_end, run_len, 0)
    best = jnp.argmax(freq, axis=-1)  # first max: earliest run = smallest value

    mi = jnp.take_along_axis(order, best[..., None], axis=-1)  # original index
    # values gathered through the differentiable take_along_axis op so the
    # tape records the mode grad (scatter into the mode's slot) — reference
    # mode_grad (backward.yaml)
    from .manipulation import squeeze, take_along_axis

    mi_orig = jnp.moveaxis(mi, -1, ax)
    mv = take_along_axis(x, Tensor(mi_orig), ax)
    if keepdim:
        return mv, Tensor(mi_orig.astype(jnp.int64))
    return squeeze(mv, ax), Tensor(jnp.squeeze(mi_orig, ax).astype(jnp.int64))

"""Fused LayerNorm Pallas kernel (forward + backward).

Reference parity: paddle/phi/kernels/gpu/layer_norm_kernel.cu (the fused
welford + affine CUDA kernel). TPU-native: rows tile over the grid, each
program normalizes a [block_rows, hidden] tile in VMEM with f32 statistics —
one HBM read per tensor in each pass instead of XLA's separate
mean/var/normalize ops. Backward recomputes xhat from saved (mu, rstd) and
produces dx in one pass; dgamma/dbeta accumulate across the sequential TPU
grid into one revisited [1, hidden] output block (the Mosaic reduction idiom —
no atomics, no partials array).

RETIRED from the nn.functional.layer_norm route in round 5 (BASELINE.md
retirement note: never completed a functional on-chip run across two chip
windows, and XLA fuses the plain lowering into the surrounding elementwise
chain, leaving little headroom). Available as a direct-call library kernel;
math pinned by tests/test_pallas_layernorm.py (interpret mode).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._common import interpret as _interpret, pick_block as _pick_block

_LANES = 128


def supported(n_rows: int, hidden: int) -> bool:
    return hidden % _LANES == 0 and n_rows >= 1


def _pick_rows(n_rows: int, hidden: int) -> int:
    # target ~1-2 MB f32 tiles; at least 8 rows for sublane alignment
    target = max(8, min(256, (1 << 19) // max(hidden, 1)))
    b = _pick_block(n_rows, preferred=target)
    return b if b <= target else 1  # pick_block falls back to n_rows itself


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                 # [rows, hidden]
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    o_ref[...] = (xhat * g_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)
    if mu_ref is not None:  # inference variant skips the residual writes
        # row stats broadcast across the lane dim (TPU per-row scalar layout)
        mu_ref[...] = jnp.broadcast_to(mu, mu_ref.shape)
        rstd_ref[...] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _infer_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    _fwd_kernel(x_ref, g_ref, b_ref, o_ref, None, None, eps=eps)


def _bwd_kernel(x_ref, g_ref, dy_ref, mu_ref, rstd_ref,
                dx_ref, dg_ref, db_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    mu = mu_ref[...][:, :1]
    rstd = rstd_ref[...][:, :1]
    xhat = (x - mu) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((wdy - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)
    # dgamma/dbeta: accumulate into one revisited [1, h] output block — TPU
    # grid steps run sequentially, so += across iterations is the Mosaic
    # reduction idiom (a [tiles, h] partials array with [1, h] blocks violates
    # the (8, 128) block-tiling rule — caught by the TPU-export gate)
    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    dg_ref[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] += jnp.sum(dy, axis=0, keepdims=True)


def _fwd(x2d, g, b, eps):
    n, h = x2d.shape
    rows = _pick_rows(n, h)
    grid = (n // rows,)
    o, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, g[None, :], b[None, :])
    return o, mu, rstd


def _infer(x2d, g, b, eps):
    """Forward-only variant: no mu/rstd residual writes to HBM."""
    n, h = x2d.shape
    rows = _pick_rows(n, h)
    return pl.pallas_call(
        functools.partial(_infer_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x2d.dtype),
        interpret=_interpret(),
    )(x2d, g[None, :], b[None, :])


def _bwd(x2d, g, dy, mu, rstd):
    n, h = x2d.shape
    rows = _pick_rows(n, h)
    tiles = n // rows
    dx, dg_part, db_part = pl.pallas_call(
        _bwd_kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2d.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=_interpret(),
    )(x2d, g[None, :], dy, mu, rstd)
    return dx, dg_part[0], db_part[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln(x2d, g, b, eps):
    # primal (no-grad) path: stats-free kernel, half the HBM writes
    return _infer(x2d, g, b, eps)


def _ln_fwd(x2d, g, b, eps):
    o, mu, rstd = _fwd(x2d, g, b, eps)
    return o, (x2d, g, mu, rstd)


def _ln_bwd(eps, res, dy):
    x2d, g, mu, rstd = res
    dx, dg, db = _bwd(x2d, g, dy, mu, rstd)
    return dx, dg.astype(g.dtype), db.astype(g.dtype)


_ln.defvjp(_ln_fwd, _ln_bwd)


def layer_norm(x, weight, bias, eps=1e-5):
    """x: [..., hidden]; weight/bias: [hidden]. Returns x's shape/dtype."""
    shape = x.shape
    h = shape[-1]
    n = math.prod(shape[:-1]) if len(shape) > 1 else 1
    out = _ln(x.reshape(n, h), weight, bias, float(eps))
    return out.reshape(shape)

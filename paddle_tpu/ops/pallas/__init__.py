"""Pallas TPU kernels for the hot ops (the analogue of the reference's hand-written
CUDA kernels under paddle/fluid/operators/fused/). Registered behind the same
functional surface (ops.nn_functional) with XLA fallbacks off-TPU."""
from .flash_attention import flash_attention, supported as flash_supported  # noqa: F401

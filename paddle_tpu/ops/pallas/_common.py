"""Shared helpers for the TPU Pallas kernels (flash_attention, lm_loss)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Index-map constants must be i32: the framework enables jax_enable_x64
# (paddle's int64 default), and a weak `0` literal would trace to i64, which
# Mosaic rejects.
I0 = np.int32(0)

NEG_INF = -1e30  # finite (not -inf): keeps exp() and Mosaic happy


def interpret() -> bool:
    """Kernels run in Pallas interpret mode on CPU (tests)."""
    return jax.default_backend() == "cpu"


def vmem(shape, dtype=jnp.float32):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)


def pick_block(n: int, preferred: int = 512) -> int:
    """Largest power-of-two tile from (preferred..8) dividing n; falls back to
    n itself (callers' supported() predicates reject unaligned sizes)."""
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and n % b == 0 and b <= n:
            return b
    return n

"""Pallas fused LM-head + softmax-cross-entropy (online over vocab tiles).

The chunked XLA version (ops/fused.py) avoids materializing the full
[tokens, vocab] logits but still writes each chunk's logits tile to HBM
between the matmul and the reduction. This kernel keeps every logits tile in
VMEM — flash-attention's online-softmax trick applied to the classifier:

    fwd:  per (row-block i, vocab-block j): s = h_i @ W_j^T (f32 acc);
          m/l online logsumexp accumulators; picked logit found in the tile
          that contains each row's label. loss = m + log(l) - picked.
    bwd:  recompute s tile-by-tile from (h, W, lse);
          p = exp(s - lse); dl = (p - onehot(label)) * g;
          dh kernel accumulates dl @ W_j over j (row-block outer),
          dW kernel accumulates dl^T @ h_i over i (vocab-block outer)
          — the same two-kernel split as flash attention's dq / dkdv.

HBM traffic per pass ~ reads of h and W only (W once per row-block), vs the
chunked version's additional logits-tile writes+reads. Saved residuals:
per-row logsumexp (f32 [tokens]).

W layout: [vocab, hidden] (tied-embedding layout). Rows must divide into
block_n, vocab into block_v — the public wrapper in ops/fused.py pads rows
and only routes here when `supported()` holds. CPU runs interpret mode.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ._common import I0 as _I0, NEG_INF, interpret as _interpret, \
    vmem as _vmem


def _pick(n: int, preferred: int) -> int:
    """Like _common.pick_block but with a 128 floor (lane-width tiles) and a
    0 'unsupported' sentinel consumed by supported()."""
    for b in (preferred, 512, 256, 128):
        if b <= preferred and n % b == 0 and b <= n:
            return b
    return 0


def _pick_rows(n: int) -> int:
    """Row blocks tile the 1D labels/loss/lse operands, whose XLA layout is
    (8 sublanes x 128 lanes) = 1024-element tiles — a smaller 1D block fails
    Mosaic layout verification on real TPU ("XLA layout {0:T(1024)} does not
    match Mosaic layout {0:T(512)}"), so 1024 is the floor, not 128."""
    return 1024 if n % 1024 == 0 and n >= 1024 else 0


def _check_block_n(v: int) -> int:
    """COMPUTE row-block size (the 2D h/s tiles). The 1D operands always use
    1024-element blocks (_pick_rows); when block_n < 1024 each 1D block is
    revisited 1024//block_n consecutive row-steps via an i//pack index map
    and pl.ds sub-slices. Mosaic compile time grows superlinearly in the
    vector-op count of the kernel body (~block_n x block_v tiles): the
    round-3 on-chip probe is what this knob exists for — at 1024x512 the
    forward alone exceeded 9.5 min of Mosaic compile."""
    v = int(v)
    if v not in (256, 512, 1024):
        raise ValueError(
            f"block_n must be 256, 512 or 1024 (the 1D operands tile at "
            f"1024 and the compute block must divide it); got {v}")
    return v


def supported(n_rows: int, vocab: int, hidden: int) -> bool:
    # vocab needs no divisibility: the wrapper pads W to a 512 multiple and the
    # kernels mask the padded columns to NEG_INF (a 50304 vocab would otherwise
    # fall to 128-wide blocks -> a 393-step inner grid and minutes of Mosaic
    # compile at bench shapes)
    return _pick_rows(n_rows) > 0 and vocab >= 128 and hidden % 128 == 0


def _row1d_index_map(pack: int):
    """Index map for the 1024-element 1D blocks revisited `pack` row-steps.
    pack == 1 avoids the traced floor_divide entirely: each index_map traces
    through several jnp layers, and at the default block the extra frames
    pushed the deeply nested export->grad->pallas stack over CPython's
    recursion limit under pytest."""
    if pack == 1:
        return lambda i, j: (i,)
    return lambda i, j: (i // pack,)


def _row1d_index_map_ji(pack: int):
    """Same but for (j, i)-ordered grids (the dW kernel)."""
    if pack == 1:
        return lambda j, i: (i,)
    return lambda j, i: (i // pack,)


# ---------------------------------------------------------------- forward ----

def _fwd_kernel(h_ref, w_ref, lab_ref, loss_ref, lse_ref, m_scr, l_scr, p_scr,
                *, block_n, block_v, v_blocks, v_true, pack):
    i = pl.program_id(0)
    j = pl.program_id(1)
    # 1D operands ride 1024-element blocks (their XLA tile); when the compute
    # block is smaller, each 1D block is revisited `pack` consecutive row
    # steps and this step touches only its ds sub-slice
    off = (i % pack) * block_n if pack > 1 else 0

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        p_scr[...] = jnp.zeros_like(p_scr)

    h = h_ref[...]                      # [bn, H] storage dtype
    w = w_ref[...]                      # [bv, H]
    s = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bn, bv]

    lab = lab_ref[pl.ds(off, block_n)]  # [bn] int32 (1D block: a [nb, bn]
    #                                     2D layout with [1, bn] blocks breaks
    #                                     Mosaic's (8, 128) block-tiling rule)
    col0 = j * block_v
    cols = col0 + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_true is not None:              # W padded to a 512 multiple: padded
        #                                 columns must not enter the logsumexp
        s = jnp.where(cols < v_true, s, jnp.float32(NEG_INF))
    hit = cols == lab[:, None]          # row's label inside this tile?
    # each label lands in exactly one tile: accumulate its logit via sum
    # zeros_like, not a 0.0 literal: under jax_enable_x64 the weak literal
    # promotes through f64 and Mosaic has no f64->f32 cast
    p_scr[...] += jnp.sum(jnp.where(hit, s, jnp.zeros_like(s)), axis=1,
                          keepdims=True)

    m_prev = m_scr[...][:, :1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    l_scr[...] = (l_scr[...] * jnp.exp(m_prev - m_new)
                  + jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True))
    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(j == v_blocks - 1)
    def _finalize():
        # the output block flushes when i crosses a pack boundary; each of the
        # pack visits fills its own sub-slice at its last vocab step
        lse = m_scr[...][:, :1] + jnp.log(l_scr[...][:, :1])
        loss_ref[pl.ds(off, block_n)] = (lse - p_scr[...][:, :1])[:, 0]
        lse_ref[pl.ds(off, block_n)] = lse[:, 0]


def _fwd(h2, w, labels, block_n, block_v, v_true=None):
    n, hdim = h2.shape
    v = w.shape[0]
    if w.dtype != h2.dtype:
        # one materialized cast (f32 master -> bf16 under amp): tiles then read
        # at half bandwidth; dW still accumulates f32 in scratch
        w = w.astype(h2.dtype)
    pack = 1024 // block_n
    grid = (n // block_n, v // block_v)
    kernel = functools.partial(_fwd_kernel, block_n=block_n, block_v=block_v,
                               v_blocks=v // block_v, v_true=v_true, pack=pack)
    row1d = _row1d_index_map(pack)
    loss, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, hdim), lambda i, j: (i, _I0)),
            pl.BlockSpec((block_v, hdim), lambda i, j: (j, _I0)),
            pl.BlockSpec((1024,), row1d),
        ],
        out_specs=[
            pl.BlockSpec((1024,), row1d),
            pl.BlockSpec((1024,), row1d),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ],
        scratch_shapes=[_vmem((block_n, 128)), _vmem((block_n, 128)),
                        _vmem((block_n, 128))],
        interpret=_interpret(),
    )(h2, w, labels)
    return loss, lse


# --------------------------------------------------------------- backward ----

def _dh_kernel(h_ref, w_ref, lab_ref, lse_ref, g_ref, dh_ref, dh_scr,
               *, block_n, block_v, v_blocks, v_true, pack):
    i = pl.program_id(0)
    j = pl.program_id(1)
    off = (i % pack) * block_n if pack > 1 else 0

    @pl.when(j == 0)
    def _init():
        dh_scr[...] = jnp.zeros_like(dh_scr)

    h = h_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    lab = lab_ref[pl.ds(off, block_n)]
    lse = lse_ref[pl.ds(off, block_n)]
    g = g_ref[pl.ds(off, block_n)]
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_true is not None:  # padded columns: p -> 0, no gradient flow
        s = jnp.where(cols < v_true, s, jnp.float32(NEG_INF))
    p = jnp.exp(s - lse[:, None])
    dl = (p - (cols == lab[:, None])) * g[:, None]       # [bn, bv] f32
    dh_scr[...] += jax.lax.dot_general(
        dl.astype(w.dtype), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == v_blocks - 1)
    def _finalize():
        dh_ref[...] = dh_scr[...].astype(dh_ref.dtype)


def _dw_kernel(h_ref, w_ref, lab_ref, lse_ref, g_ref, dw_ref, dw_scr,
               *, block_n, block_v, n_blocks, v_true, pack):
    j = pl.program_id(0)
    i = pl.program_id(1)
    off = (i % pack) * block_n if pack > 1 else 0

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)

    h = h_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(h, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    lab = lab_ref[pl.ds(off, block_n)]
    lse = lse_ref[pl.ds(off, block_n)]
    g = g_ref[pl.ds(off, block_n)]
    cols = j * block_v + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    if v_true is not None:  # padded columns contribute zero to dW rows >= v_true
        s = jnp.where(cols < v_true, s, jnp.float32(NEG_INF))
    p = jnp.exp(s - lse[:, None])
    dl = (p - (cols == lab[:, None])) * g[:, None]
    dw_scr[...] += jax.lax.dot_general(
        dl.astype(h.dtype), h, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [bv, H]

    @pl.when(i == n_blocks - 1)
    def _finalize():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _bwd(res, g, block_n, block_v, v_true=None):
    h2, w, labels, lse = res
    w_dtype = w.dtype
    if w.dtype != h2.dtype:
        w = w.astype(h2.dtype)
    n, hdim = h2.shape
    v = w.shape[0]
    pack = 1024 // block_n
    nb, vb = n // block_n, v // block_v
    g32 = g.astype(jnp.float32)

    row1d = _row1d_index_map(pack)
    dh = pl.pallas_call(
        functools.partial(_dh_kernel, block_n=block_n, block_v=block_v,
                          v_blocks=vb, v_true=v_true, pack=pack),
        grid=(nb, vb),
        in_specs=[
            pl.BlockSpec((block_n, hdim), lambda i, j: (i, _I0)),
            pl.BlockSpec((block_v, hdim), lambda i, j: (j, _I0)),
            pl.BlockSpec((1024,), row1d),
            pl.BlockSpec((1024,), row1d),
            pl.BlockSpec((1024,), row1d),
        ],
        out_specs=pl.BlockSpec((block_n, hdim), lambda i, j: (i, _I0)),
        out_shape=jax.ShapeDtypeStruct((n, hdim), h2.dtype),
        scratch_shapes=[_vmem((block_n, hdim))],
        interpret=_interpret(),
    )(h2, w, labels, lse, g32)

    row1d_ji = _row1d_index_map_ji(pack)
    dw = pl.pallas_call(
        functools.partial(_dw_kernel, block_n=block_n, block_v=block_v,
                          n_blocks=nb, v_true=v_true, pack=pack),
        grid=(vb, nb),
        in_specs=[
            pl.BlockSpec((block_n, hdim), lambda j, i: (i, _I0)),
            pl.BlockSpec((block_v, hdim), lambda j, i: (j, _I0)),
            pl.BlockSpec((1024,), row1d_ji),
            pl.BlockSpec((1024,), row1d_ji),
            pl.BlockSpec((1024,), row1d_ji),
        ],
        out_specs=pl.BlockSpec((block_v, hdim), lambda j, i: (j, _I0)),
        out_shape=jax.ShapeDtypeStruct((v, hdim), jnp.float32),
        scratch_shapes=[_vmem((block_v, hdim))],
        interpret=_interpret(),
    )(h2, w, labels, lse, g32)
    return dh, dw.astype(w_dtype)  # f32 scratch accumulation -> master dtype


# ------------------------------------------------------------- public API ----

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _lm_loss(h2, w, labels, block_n, block_v, v_true):
    loss, _ = _fwd(h2, w, labels, block_n, block_v, v_true)
    return loss


def _fwd_rule(h2, w, labels, block_n, block_v, v_true):
    loss, lse = _fwd(h2, w, labels, block_n, block_v, v_true)
    return loss, (h2, w, labels, lse)


def _bwd_rule(block_n, block_v, v_true, res, g):
    dh, dw = _bwd(res, g, block_n, block_v, v_true)
    dlab = np.zeros(res[2].shape, dtype=jax.dtypes.float0)
    return dh, dw, dlab


_lm_loss.defvjp(_fwd_rule, _bwd_rule)


def lm_head_cross_entropy(h2, w, labels, block_n=256):
    """h2 [N, H], w [V, H], labels [N] int32 (already ignore-masked to a safe
    index by the caller) -> per-row loss [N] f32. Caller guarantees
    supported(N, V, H). W is padded to a 512-multiple vocab internally (padded
    columns masked to NEG_INF; dW for them is zero and sliced off by autodiff
    of the pad). RETIRED from the training path (BASELINE.md round 5): not
    routed by ops/fused.py; available as a direct-call library kernel only.

    block_n hazard: 1024 is the documented Mosaic compile pathology at bench
    vocab (50304 -> the round-3 probe measured >9.5 min of Mosaic compile for
    the forward alone at 1024x512 and wedged the chip tunnel twice,
    BASELINE.md round 3) — compile time grows superlinearly in the kernel
    body's tile count. The default is therefore 256, the value bench actually
    shipped; only raise it at small vocab after probing compile time
    (tools/lmloss_compile_probe.py)."""
    n = h2.shape[0]
    v = w.shape[0]
    assert _pick_rows(n) == 1024  # callers pad rows to a 1024 multiple
    block_n = _check_block_n(block_n)
    vpad = (-v) % 512
    if vpad:
        w = jnp.concatenate(
            [w, jnp.zeros((vpad, w.shape[1]), w.dtype)], axis=0)
    block_v = _pick(w.shape[0], 512)
    return _lm_loss(h2, w, labels.astype(jnp.int32), block_n, block_v,
                    v if vpad else None)

"""Pallas TPU flash-attention kernel (forward + backward).

This is the TPU-native replacement for the reference's fused CUDA attention
(`paddle/fluid/operators/fused/fused_attention_op.cu`, `fmha` kernels): an
online-softmax tiled attention that never materializes the [s, s] score matrix,
keeping the working set in VMEM and the two matmuls per tile on the MXU.

Layout: [b, h, s, d] inside the kernels (batch*heads collapsed into one grid
dim). The public entry `flash_attention` takes paddle's [b, s, h, d].

Backward follows the FlashAttention-2 scheme: forward saves per-row
logsumexp; backward recomputes P tile-by-tile, with one kernel producing
dK/dV (kv-block outer loop) and one producing dQ (q-block outer loop).

On CPU (tests) the kernels run in Pallas interpret mode.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ._common import I0 as _I0, NEG_INF, interpret as _interpret, \
    pick_block as _pick_block, vmem as _vmem


def supported(seq_q: int, seq_k: int, head_dim: int) -> bool:
    """Shapes the kernel handles; callers fall back to the XLA path otherwise.

    The picked block is the sublane dim of the q/k tiles, so it must be a
    multiple of 8 (f32 tiling) — _pick_block falls back to the raw length for
    primes/unaligned lengths, which Mosaic would reject at compile time.
    """
    return (
        seq_q >= 8
        and seq_k >= 8
        and _pick_block(seq_q) % 8 == 0
        and _pick_block(seq_k) % 8 == 0
        and head_dim % 8 == 0
    )


# ---------------------------------------------------------------- forward ----

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k, kv_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: a kv block strictly above the diagonal contributes nothing
    run = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(run)
    def _compute():
        # matmul inputs stay in their storage dtype (bf16 under amp) so the MXU
        # runs at bf16 rate; accumulation is forced to f32 via
        # preferred_element_type — casting inputs to f32 here would quarter
        # matmul throughput on v5e for no accuracy gain over f32 accumulation.
        q = q_ref[0]  # [bq, d]
        k = k_ref[0]  # [bk, d]
        v = v_ref[0]  # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale  # [bq, bk] f32
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, jnp.float32(NEG_INF))

        m_prev = m_scr[...][:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)       # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                          # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                 # [bq, 1]
        l_new = alpha * l_scr[...][:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        l = l_scr[...][:, :1]
        # fully-masked rows -> zeros, not NaN. ones_like (not a python 1.0
        # literal): under jax_enable_x64 the weak literal promotes through
        # f64 and Mosaic has no f64->f32 cast — caught by the TPU-export gate
        l = jnp.where(l == 0.0, jnp.ones_like(l), l)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        # lse broadcast across the 128-lane dim (TPU block layout for row stats)
        lse_ref[0] = jnp.broadcast_to(m_scr[...][:, :1] + jnp.log(l), lse_ref.shape[1:])


def _fwd(q, k, v, sm_scale, causal, blocks=None):
    """q,k,v: [bh, s, d] -> (o [bh, sq, d], lse [bh, sq] f32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = blocks if blocks else (_pick_block(sq), _pick_block(sk))
    kv_blocks = sk // bk
    grid = (bh, sq // bq, kv_blocks)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, kv_blocks=kv_blocks)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 128), jnp.float32),
        ],
        scratch_shapes=[_vmem((bq, 128)), _vmem((bq, 128)), _vmem((bq, d))],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------- backward ----

def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr,
                     *, sm_scale, causal, block_q, block_k, q_blocks):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(run)
    def _compute():
        # storage-dtype (bf16) matmul inputs + f32 accumulation, as in forward
        q = q_ref[0]                            # [bq, d]
        k = k_ref[0]                            # [bk, d]
        v = v_ref[0]                            # [bk, d]
        do = do_ref[0]                          # [bq, d]
        lse = lse_ref[0][:, :1]                 # [bq, 1]
        delta = delta_ref[0][:, :1]             # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = ki * 0 + qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse)                    # [bq, bk] f32
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr,
                   *, sm_scale, causal, block_q, block_k, kv_blocks):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = (qi + 1) * block_q > ki * block_k if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd(res, g, sm_scale, causal, blocks=None, g_lse=None):
    q, k, v, o, lse = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    bq, bk = blocks if blocks else (_pick_block(sq), _pick_block(sk))
    q_blocks, kv_blocks = sq // bq, sk // bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        # lse cotangent folds into delta: dS = P*(dP - delta) + P*g_lse
        #                                    = P*(dP - (delta - g_lse))
        delta = delta - g_lse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, 128))  # lane-broadcast layout

    dkdv_kernel = functools.partial(
        _bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, q_blocks=q_blocks)
    dk, dv = pl.pallas_call(
        dkdv_kernel,
        grid=(bh, kv_blocks, q_blocks),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _I0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),   # v
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, _I0)),   # do
            pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, _I0)),  # lse
            pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, _I0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, _I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[_vmem((bk, d)), _vmem((bk, d))],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk, kv_blocks=kv_blocks)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, _I0)),
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, _I0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, _I0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, _I0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[_vmem((bq, d))],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- public API ----

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, sm_scale, causal, blocks):
    o, _ = _fwd(q, k, v, sm_scale, causal, blocks)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, blocks):
    o, lse = _fwd(q, k, v, sm_scale, causal, blocks)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(sm_scale, causal, blocks, res, g):
    return _bwd(res, g, sm_scale, causal, blocks)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd_lse(q, k, v, sm_scale, causal, blocks):
    """Like _flash_bhsd but also returns the per-row logsumexp [bh, sq] —
    the residual ring attention needs to merge partial blocks; both outputs
    carry cotangents (lse's folds into delta in _bwd)."""
    o, lse = _fwd(q, k, v, sm_scale, causal, blocks)
    return o, lse[..., 0]


def _flash_lse_fwd_rule(q, k, v, sm_scale, causal, blocks):
    o, lse = _fwd(q, k, v, sm_scale, causal, blocks)
    return (o, lse[..., 0]), (q, k, v, o, lse)


def _flash_lse_bwd_rule(sm_scale, causal, blocks, res, g):
    g_o, g_lse = g
    return _bwd(res, g_o, sm_scale, causal, blocks, g_lse=g_lse)


_flash_bhsd_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None):
    """q,k,v: [b, s, h, d]. Returns (out [b, sq, h, d], lse [b, h, sq] f32).

    The (out, lse) pair is what a ring-attention shard needs to merge partial
    KV-block results with online softmax (SURVEY §5.7); both are
    differentiable through the Pallas backward kernels.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    def to_bhsd(x):
        s = x.shape[1]
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, x.shape[-1])

    blocks = _tuned_blocks(b * h, sq, sk, d, q.dtype, float(sm_scale),
                           bool(causal))
    o, lse = _flash_bhsd_lse(to_bhsd(q), to_bhsd(k), to_bhsd(v),
                             float(sm_scale), bool(causal), tuple(blocks))
    return (jnp.swapaxes(o.reshape(b, h, sq, d), 1, 2),
            lse.reshape(b, h, sq))


def _tuned_blocks(bh, sq, sk, d, dtype, sm_scale, causal):
    """Block-size choice via the kernel autotune cache (core/autotune.py — the
    phi AlgorithmsCache analogue). Tuning runs the forward kernel out-of-band
    on materialized random inputs, so it is legal mid-trace; when autotune is
    off this collapses to the static heuristic."""
    from ...core import autotune

    default = (_pick_block(sq), _pick_block(sk))
    key = (int(bh), int(sq), int(sk), int(d), str(dtype), bool(causal),
           jax.default_backend())
    if not autotune.enabled():
        # peek (non-counting): a disabled run must not skew hit-rate stats
        cached = autotune.cache().peek("flash_attention", key)
        return cached or default
    cached = autotune.cache().get("flash_attention", key)
    if cached is not None:
        return cached
    if not autotune.should_tune():  # closed window / multi-controller: no timing
        return default
    # 1024 joins the space only where the BACKWARD working set fits: the
    # tuned choice is shared with the bwd kernels (which the tuner also
    # compiles + times, see below), whose bodies hold ~4 score-sized f32 intermediates
    # (s/p/dp/ds) — so the guard budgets 4 * bq * bk * 4 B <= 8 MB of
    # v5e's 16 MB VMEM, admitting (512,1024)/(1024,512) but not
    # (1024,1024), whose ~16 MB bwd set would spill or fail Mosaic. At
    # the bench shape (seq 1024) the {128,256,512} space degenerated to
    # the heuristic's own choice — the tuned [512,512] equaled
    # pick_block's default, so the round-5 "autotune win" was run-to-run
    # variance; the 1024-rect blocks are the first candidates the
    # heuristic cannot reach.
    candidates = sorted({(q_, k_)
                         for q_ in (1024, 512, 256, 128)
                         for k_ in (1024, 512, 256, 128)
                         if sq % q_ == 0 and sk % k_ == 0
                         and 4 * q_ * k_ * 4 <= (8 << 20)}) or [default]
    if len(candidates) == 1:
        return candidates[0]

    rng = np.random.RandomState(0)
    qa = jnp.asarray(rng.randn(bh, sq, d), dtype=dtype)
    ka = jnp.asarray(rng.randn(bh, sk, d), dtype=dtype)
    va = jnp.asarray(rng.randn(bh, sk, d), dtype=dtype)

    # one jitted executable per candidate, shared by the warmup and timed calls
    # (a fresh lambda per call would re-compile and time the compiler instead).
    # The tuned choice binds the FA2 BACKWARD kernels too (the pick is reused
    # at training time), so each candidate is compiled AND timed through
    # value_and_grad: fwd + both bwd kernels. A block pair whose backward
    # fails Mosaic compile raises here and is skipped by pick() — it can no
    # longer win on forward time and then fail only at training time
    # (ADVICE r5 #1), and the argmin now optimizes the full train-step cost.
    def _make_fb(blocks):
        def loss(a, b, c):
            return jnp.sum(
                _flash_bhsd(a, b, c, sm_scale, causal, blocks)
                .astype(jnp.float32))

        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    compiled = {blocks: _make_fb(blocks) for blocks in candidates}

    def run(blocks):
        dq, dk, dv = compiled[blocks](qa, ka, va)
        np.asarray(dq[0, 0, 0])  # D2H sync (block_until_ready can return
        np.asarray(dk[0, 0, 0])  # early through a remote PJRT tunnel); the
        np.asarray(dv[0, 0, 0])  # grads drain both backward kernels

    return autotune.pick("flash_attention", key, candidates, run, default=default)


def flash_attention(q, k, v, causal: bool = False, sm_scale: float | None = None):
    """q,k,v: [b, s, h, d] (paddle layout). Returns [b, sq, h, d]."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # [b, s, h, d] -> [b*h, s, d]
    def to_bhsd(x):
        s = x.shape[1]
        return jnp.swapaxes(x, 1, 2).reshape(b * h, s, x.shape[-1])

    blocks = _tuned_blocks(b * h, sq, sk, d, q.dtype, float(sm_scale), bool(causal))
    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), float(sm_scale), bool(causal),
                    tuple(blocks))
    return jnp.swapaxes(o.reshape(b, h, sq, d), 1, 2)

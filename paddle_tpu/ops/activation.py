"""Activations. Reference: python/paddle/nn/functional/activation.py + phi activation kernels.
All are single fused XLA expressions (elementwise — fused into neighbors by XLA)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ._helpers import t_, unary

relu = unary("relu", jax.nn.relu)
relu6 = unary("relu6", jax.nn.relu6)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
silu = unary("silu", jax.nn.silu)
tanh = unary("tanh", jnp.tanh)
softsign = unary("softsign", jax.nn.soft_sign)
tanhshrink = unary("tanhshrink", lambda x: x - jnp.tanh(x))
mish = unary("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
hardswish = unary("hardswish", jax.nn.hard_swish)
hardsigmoid = unary("hardsigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
log_sigmoid = unary("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return apply("gelu", lambda a, approximate: jax.nn.gelu(a, approximate=approximate),
                 [t_(x)], {"approximate": bool(approximate)})


def swish(x, name=None):
    return silu(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply("leaky_relu", lambda a, ns: jax.nn.leaky_relu(a, ns), [t_(x)],
                 {"ns": negative_slope})


def elu(x, alpha=1.0, name=None):
    return apply("elu", lambda a, alpha: jax.nn.elu(a, alpha), [t_(x)], {"alpha": alpha})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply("selu", lambda a, scale, alpha: scale * jnp.where(
        a > 0, a, alpha * jnp.expm1(a)), [t_(x)], {"scale": scale, "alpha": alpha})


def celu(x, alpha=1.0, name=None):
    return apply("celu", lambda a, alpha: jax.nn.celu(a, alpha), [t_(x)], {"alpha": alpha})


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = t_(x), t_(weight)

    def kernel(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)

    return apply("prelu", kernel, [x, weight])


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    from ..core import random as random_mod

    x = t_(x)
    if training:
        key = random_mod.next_key()
        slope = jax.random.uniform(key, x._data.shape, x._data.dtype, lower, upper)
    else:
        slope = (lower + upper) / 2.0

    def kernel(a):
        return jnp.where(a >= 0, a, slope * a)

    return apply("rrelu", kernel, [x])


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply("hardtanh", lambda a, lo, hi: jnp.clip(a, lo, hi), [t_(x)],
                 {"lo": min, "hi": max})


def hardshrink(x, threshold=0.5, name=None):
    return apply("hardshrink", lambda a, t: jnp.where(jnp.abs(a) > t, a, 0.0), [t_(x)],
                 {"t": threshold})


def softshrink(x, threshold=0.5, name=None):
    return apply("softshrink", lambda a, t: jnp.where(
        a > t, a - t, jnp.where(a < -t, a + t, 0.0)), [t_(x)], {"t": threshold})


def thresholded_relu(x, threshold=1.0, name=None):
    return apply("thresholded_relu", lambda a, t: jnp.where(a > t, a, 0.0), [t_(x)],
                 {"t": threshold})


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply("softplus", lambda a, beta, threshold: jnp.where(
        beta * a > threshold, a, jax.nn.softplus(beta * a) / beta), [t_(x)],
        {"beta": beta, "threshold": threshold})


def softmax(x, axis=-1, dtype=None, name=None):
    from ..core import dtype as dtypes

    d = dtypes.convert_dtype(dtype) if dtype else None

    def kernel(a, axis):
        if d is not None:
            a = a.astype(d)
        return jax.nn.softmax(a, axis=axis)

    return apply("softmax", kernel, [t_(x)], {"axis": axis})


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ..core import dtype as dtypes

    d = dtypes.convert_dtype(dtype) if dtype else None

    def kernel(a, axis):
        if d is not None:
            a = a.astype(d)
        return jax.nn.log_softmax(a, axis=axis)

    return apply("log_softmax", kernel, [t_(x)], {"axis": axis})


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core import random as random_mod

    x = t_(x)
    key = random_mod.next_key()
    g = -jnp.log(-jnp.log(jax.random.uniform(key, x._data.shape, x._data.dtype, 1e-20, 1.0)))

    def kernel(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            y_hard = (y == y.max(axis=axis, keepdims=True)).astype(y.dtype)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return apply("gumbel_softmax", kernel, [x])


def maxout(x, groups, axis=1, name=None):
    def kernel(a, groups, axis):
        # consecutive channels form a group: out[c] = max_g in[c*groups + g]
        # (reference: paddle/fluid/operators/math/maxouting.cc:48 input_idx)
        axis = axis % a.ndim  # paddle allows axis=-1 for NHWC
        shape = list(a.shape)
        c = shape[axis]
        new_shape = shape[:axis] + [c // groups, groups] + shape[axis + 1:]
        return jnp.max(a.reshape(new_shape), axis=axis + 1)

    return apply("maxout", kernel, [t_(x)], {"groups": groups, "axis": axis})


def glu(x, axis=-1, name=None):
    return apply("glu", lambda a, axis: jax.nn.glu(a, axis=axis), [t_(x)], {"axis": axis})


def swiglu(x, y=None, name=None):
    if y is not None:
        return apply("swiglu", lambda a, b: jax.nn.silu(a) * b, [t_(x), t_(y)])

    def kernel(a):
        a, b = jnp.split(a, 2, axis=-1)
        return jax.nn.silu(a) * b

    return apply("swiglu", kernel, [t_(x)])


# in-place variants (reference nn/functional/activation.py relu_/elu_/...):
# jnp arrays are immutable, so "in-place" rebinds the tensor's buffer like the
# reference's inplace ops rebind the variable's allocation.
def _make_inplace(fn):
    def op(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x.set_value(out._data)
        return x

    return op


relu_ = _make_inplace(relu)
elu_ = _make_inplace(elu)
softmax_ = _make_inplace(softmax)


def tanh_(x, name=None):
    x.set_value(jnp.tanh(x._data))
    return x

"""Tensor creation ops.

Reference parity: python/paddle/tensor/creation.py (to_tensor, zeros, ones, full, arange,
linspace, eye, tril/triu, diag, meshgrid, assign, clone) lowering to phi full/arange kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core import random as random_mod
from ..core.dispatch import apply, as_tensor
from ..core.place import get_place, Place
from ..core.tensor import Tensor
from ._helpers import t_


def _put(data, place=None):
    if place is not None and isinstance(place, Place):
        data = jax.device_put(data, place.jax_device())
    return data


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item()) for s in shape)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        out = data.astype(dtype) if dtype is not None else Tensor(data._data)
        out.stop_gradient = stop_gradient
        return out
    a = np.asarray(data)
    if dtype is not None:
        a = a.astype(dtypes.convert_dtype(dtype))
    elif a.dtype == np.float64:
        a = a.astype(dtypes.get_default_dtype())
    # jnp.array (copy) — asarray may alias the caller's numpy buffer on CPU, and
    # to_tensor promises an independent copy (reference semantics).
    return Tensor(_put(jnp.array(a), place), stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    return Tensor(jnp.zeros(_shape(shape), dtype))


def ones(shape, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    return Tensor(jnp.ones(_shape(shape), dtype))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is not None:
        dtype = dtypes.convert_dtype(dtype)
        return Tensor(jnp.full(_shape(shape), fill_value, dtype))
    if isinstance(fill_value, float):
        return Tensor(jnp.full(_shape(shape), fill_value, dtypes.get_default_dtype()))
    return Tensor(jnp.full(_shape(shape), fill_value))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = t_(x)
    d = dtypes.convert_dtype(dtype) if dtype else None
    return Tensor(jnp.zeros_like(x._data, d))


def ones_like(x, dtype=None, name=None):
    x = t_(x)
    d = dtypes.convert_dtype(dtype) if dtype else None
    return Tensor(jnp.ones_like(x._data, d))


def full_like(x, fill_value, dtype=None, name=None):
    x = t_(x)
    d = dtypes.convert_dtype(dtype) if dtype else None
    return Tensor(jnp.full_like(x._data, fill_value, dtype=d))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            dtype = dtypes.get_default_dtype()
        else:
            dtype = dtypes.int64
    else:
        dtype = dtypes.convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x

    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)), dtype=dtype))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=dtype))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=dtype))


def tril(x, diagonal=0, name=None):
    return apply("tril", lambda a, diagonal: jnp.tril(a, diagonal), [t_(x)], {"diagonal": int(diagonal)})


def triu(x, diagonal=0, name=None):
    return apply("triu", lambda a, diagonal: jnp.triu(a, diagonal), [t_(x)], {"diagonal": int(diagonal)})


def diag(x, offset=0, padding_value=0, name=None):
    x = t_(x)
    if x.ndim == 1 and padding_value != 0:
        def k(a, offset, padding_value):
            d = jnp.diag(a, offset)
            mask = jnp.eye(d.shape[0], dtype=bool, k=offset) if False else None
            n = a.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, a.dtype)
            idx = jnp.arange(a.shape[0])
            r = idx if offset >= 0 else idx - offset
            c = idx + offset if offset >= 0 else idx
            return out.at[r, c].set(a)
        return apply("diag", k, [x], {"offset": int(offset), "padding_value": padding_value})
    return apply("diag", lambda a, offset: jnp.diag(a, offset), [x], {"offset": int(offset)})


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda a, offset: jnp.diagflat(a, offset), [t_(x)], {"offset": int(offset)})


def _diag_rc(n, offset):
    """(row, col) index arrays of an n-element diagonal at `offset` (shared
    by diag_embed / fill_diagonal_tensor so offset handling cannot drift)."""
    idx = jnp.arange(n)
    r = idx if offset >= 0 else idx - offset
    c = idx + offset if offset >= 0 else idx
    return r, c


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal embedding (reference diag_embed_op.cc semantics):
    the last dim of `input` becomes the (offset) diagonal of a new matrix
    spanned by dims (dim1, dim2) of the output."""
    x = t_(input)

    def k(a, offset, dim1, dim2):
        n = a.shape[-1] + abs(offset)
        out_ndim = a.ndim + 1
        d1, d2 = dim1 % out_ndim, dim2 % out_ndim
        r, c = _diag_rc(a.shape[-1], offset)
        # build with (row, col) as the LAST two axes, then move them home
        mat = jnp.zeros(a.shape[:-1] + (n, n), a.dtype).at[..., r, c].set(a)
        return jnp.moveaxis(mat, (-2, -1), (d1, d2))

    return apply("diag_embed", k, [x],
                 {"offset": int(offset), "dim1": int(dim1),
                  "dim2": int(dim2)})


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write `y` onto the (offset) diagonal spanned by (dim1, dim2) of a
    COPY of x (reference fill_diagonal_tensor_op.cc)."""
    x, y = t_(x), t_(y)

    def k(a, b, offset, dim1, dim2):
        d1 = dim1 % a.ndim
        d2 = dim2 % a.ndim
        m = jnp.moveaxis(a, (d1, d2), (-2, -1))
        nr, nc = m.shape[-2], m.shape[-1]
        dlen = min(nr, nc - offset) if offset >= 0 else min(nr + offset, nc)
        r, c = _diag_rc(dlen, offset)
        m = m.at[..., r, c].set(b.astype(a.dtype))
        return jnp.moveaxis(m, (-2, -1), (d1, d2))

    return apply("fill_diagonal_tensor", k, [x, y],
                 {"offset": int(offset), "dim1": int(dim1),
                  "dim2": int(dim2)})


def meshgrid(*args, **kwargs):
    tensors = [t_(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*[t._data for t in tensors], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    x = t_(x) if not isinstance(x, (np.ndarray, list, tuple, int, float)) else to_tensor(x)
    out = apply("assign", lambda a: a + 0, [x])
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x, name=None):
    return apply("clone", lambda a: a + 0, [t_(x)])


def numel(x, name=None):
    return Tensor(jnp.asarray(t_(x).size, dtypes.int64))


def tril_indices(row, col, offset=0, dtype=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtypes.convert_dtype(dtype or "int64")))


def triu_indices(row, col=None, offset=0, dtype=None):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtypes.convert_dtype(dtype or "int64")))


def clone_detached(x):
    return Tensor(t_(x)._data)


# ---- random creation (stateful dygraph surface over functional JAX RNG) ----

def rand(shape, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    key = random_mod.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype))


def randn(shape, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    key = random_mod.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = ()
    key = random_mod.next_key()
    out = jax.random.normal(key, _shape(shape) if shape != () else (), dtypes.get_default_dtype())
    return Tensor(out * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.get_default_dtype()
    key = jax.random.key(seed) if seed else random_mod.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype, minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.int64
    key = random_mod.next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high, dtype))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = t_(x)
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype=None, name=None):
    dtype = dtypes.convert_dtype(dtype) if dtype else dtypes.int64
    key = random_mod.next_key()
    return Tensor(jax.random.permutation(key, int(n)).astype(dtype))


def bernoulli(x, name=None):
    x = t_(x)
    key = random_mod.next_key()
    return Tensor(jax.random.bernoulli(key, x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = t_(x)
    key = random_mod.next_key()
    p = x._data / x._data.sum(-1, keepdims=True)
    if x.ndim == 1:
        out = jax.random.choice(key, x.shape[0], (num_samples,), replace=replacement, p=p)
    else:
        keys = jax.random.split(key, x.shape[0])
        out = jnp.stack([
            jax.random.choice(k, x.shape[-1], (num_samples,), replace=replacement, p=p[i])
            for i, k in enumerate(keys)
        ])
    return Tensor(out.astype(dtypes.int64))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def poisson(x, name=None):
    x = t_(x)
    key = random_mod.next_key()
    return Tensor(jax.random.poisson(key, x._data).astype(x.dtype))

"""paddle.reader: legacy reader decorators (reference python/paddle/reader/
decorator.py). Kept for API parity with old-style input pipelines."""
from __future__ import annotations

import queue as _queue
import random as _random
import threading as _threading


def shuffle(reader, buf_size):
    def reader_():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def buffered(reader, size):
    """Decorate `reader` with a bounded background buffer of `size` items.

    Reference semantics (python/paddle/reader/decorator.py buffered): a
    producer thread runs the underlying reader up to `size` items ahead so
    the consumer only pays residual wait. Producer exceptions re-raise at the
    consumer; closing the returned generator stops the producer thread."""
    _DONE = object()

    def reader_():
        q = _queue.Queue(maxsize=max(1, int(size)))
        stop = _threading.Event()

        def produce():
            try:
                for item in reader():
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.1)
                            break
                        except _queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                if not stop.is_set():
                    q.put(("__error__", e))
                return
            q.put(_DONE)

        t = _threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _DONE:
                    return
                if isinstance(item, tuple) and len(item) == 2 \
                        and item[0] == "__error__":
                    raise item[1]
                yield item
        finally:
            stop.set()
            while True:  # unblock a producer stuck on a full queue
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            t.join(timeout=1.0)

    return reader_


def chain(*readers):
    def reader_():
        for r in readers:
            yield from r()

    return reader_


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    def reader_():
        iters = [iter(r()) for r in readers]
        while True:
            items = []
            stopped = 0
            for it in iters:
                try:
                    items.append(next(it))
                except StopIteration:
                    stopped += 1
            if stopped:
                if check_alignment and stopped != len(iters):
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for item in items:
                out.extend(item if isinstance(item, tuple) else (item,))
            yield tuple(out)

    return reader_


def firstn(reader, n):
    def reader_():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return reader_


def map_readers(func, *readers):
    def reader_():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader_

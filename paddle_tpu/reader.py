"""paddle.reader: legacy reader decorators (reference python/paddle/reader/
decorator.py). Kept for API parity with old-style input pipelines."""
from __future__ import annotations

import random as _random


def shuffle(reader, buf_size):
    def reader_():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def buffered(reader, size):
    def reader_():
        yield from reader()  # single-process parity shim

    return reader_


def chain(*readers):
    def reader_():
        for r in readers:
            yield from r()

    return reader_


def compose(*readers):
    def reader_():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                out.extend(it if isinstance(it, tuple) else (it,))
            yield tuple(out)

    return reader_


def firstn(reader, n):
    def reader_():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return reader_


def map_readers(func, *readers):
    def reader_():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader_

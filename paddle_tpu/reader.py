"""paddle.reader: legacy reader decorators (reference python/paddle/reader/
decorator.py). Kept for API parity with old-style input pipelines."""
from __future__ import annotations

import random as _random


def shuffle(reader, buf_size):
    def reader_():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return reader_


def buffered(reader, size):
    def reader_():
        yield from reader()  # single-process parity shim

    return reader_


def chain(*readers):
    def reader_():
        for r in readers:
            yield from r()

    return reader_


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment=True):
    def reader_():
        iters = [iter(r()) for r in readers]
        while True:
            items = []
            stopped = 0
            for it in iters:
                try:
                    items.append(next(it))
                except StopIteration:
                    stopped += 1
            if stopped:
                if check_alignment and stopped != len(iters):
                    raise ComposeNotAligned(
                        "composed readers have different lengths")
                return
            out = []
            for item in items:
                out.extend(item if isinstance(item, tuple) else (item,))
            yield tuple(out)

    return reader_


def firstn(reader, n):
    def reader_():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return reader_


def map_readers(func, *readers):
    def reader_():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader_

"""Backend capability probes shared by contracts, gates, and the CLI.

The one that matters today: does this XLA pipeline run the
AllReduceCombiner? Collective-SHAPE contracts (a handful of fused
all-reduces for N params) only hold where it does — TPU/GPU. This
container's XLA CPU keeps one all-reduce per operand and resharding
emits device-order collective-permutes, so every contract marked
``requires_combining`` is *skipped* (not weakened) on it. This predicate
used to live as a private lru-cached helper inside
tests/test_hlo_perf_gates.py; the analyzer and the 4 probe-skipped gates
now share this single copy, so "which backends can gate collectives" has
exactly one answer.
"""
from __future__ import annotations

import functools
import re
from typing import Optional

_ALL_REDUCE_OP = re.compile(r"^\s*%?all-reduce[.\d]*\s*=", re.MULTILINE)


@functools.lru_cache(maxsize=1)
def collective_combining_reason() -> Optional[str]:
    """None when the backend combines collectives (contracts must run),
    else the human-readable skip reason.

    Probe: compile a tiny TWO-parameter psum program and count all-reduce
    ops — a combining backend (TPU, GPU) folds them into one variadic
    all-reduce; the reduced CPU pipeline keeps one per operand. Cached:
    one ~100ms compile per process, at first use rather than import.
    """
    import jax
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        return "single-device backend: no collectives to gate"
    mesh = Mesh(np.array(devs), ("dp",))

    def two_psums(a, b):
        return jax.lax.psum(a, "dp"), jax.lax.psum(b, "dp")

    fm = shard_map(two_psums, mesh=mesh,
                   in_specs=(P("dp"), P("dp")), out_specs=(P(), P()))
    z = np.zeros((len(devs), 4), np.float32)
    txt = jax.jit(fm).lower(z, z).compile().as_text()
    n = len(_ALL_REDUCE_OP.findall(txt))
    if n <= 1:
        return None
    return (f"XLA {jax.default_backend()} backend does not run the "
            f"AllReduceCombiner (probe: 2-param psum compiled to {n} "
            f"all-reduce ops, a combining backend emits 1 fused) — "
            f"collective-shape gates need a TPU/GPU pipeline")


def backend_combines_collectives() -> bool:
    return collective_combining_reason() is None


@functools.lru_cache(maxsize=1)
def native_bf16_collective_reason() -> Optional[str]:
    """None when the backend keeps bf16 collective payloads in bf16 on the
    wire (wire-dtype contracts must run), else the skip reason.

    Probe: compile a bf16 psum and look at the all-reduce's payload dtype.
    CPU's float-normalization pass legalizes bf16 compute to f32, turning
    ``convert_f32(psum(convert_bf16(x)))`` into an f32 all-reduce — so on
    such backends a declared-bf16 grad-comm region ALWAYS shows f32
    reduction payloads and the dtype-upcast pass must skip, not fail.
    """
    import jax
    import numpy as np
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 2:
        return "single-device backend: no collectives to gate"
    mesh = Mesh(np.array(devs), ("dp",))

    def halfwire(a):
        return jax.lax.psum(a.astype(jax.numpy.bfloat16),
                            "dp").astype(jax.numpy.float32)

    fm = shard_map(halfwire, mesh=mesh, in_specs=(P("dp"),),
                   out_specs=P())
    z = np.zeros((len(devs), 4), np.float32)
    txt = jax.jit(fm).lower(z).compile().as_text()
    for line in txt.splitlines():
        # result dtype sits between '=' and the 'all-reduce(' call; the
        # metadata tail can spell any dtype in op_name, so don't scan it
        if (_ALL_REDUCE_OP.match(line)
                and "bf16[" in line.split("all-reduce(", 1)[0]):
            return None
    return (f"XLA {jax.default_backend()} backend upcasts bf16 collective "
            f"payloads to f32 (float normalization legalizes bf16 compute) "
            f"— wire-dtype contracts need a TPU/GPU pipeline")


def backend_keeps_bf16_on_wire() -> bool:
    return native_bf16_collective_reason() is None


def aot_serving_reason(device_count: Optional[int] = None,
                       platform: Optional[str] = None) -> Optional[str]:
    """None when AOT serving precompilation is safe on this backend, else
    the human-readable skip reason.

    Cache-SERVED multi-device executables are nondeterministic on this
    jax/XLA CPU (the collective-result leak core.compile_cache documents),
    and the AOT warm-start bundle exists precisely to serve executables
    from the persistent store — so a multi-device CPU serving mesh must
    fall back to lazy compilation rather than risk replica divergence.
    Single-device (any platform) and TPU/GPU meshes precompile freely.

    ``device_count``/``platform`` are injectable for tests; the live values
    come from jax at call time (NOT lru-cached: serving meshes reform)."""
    if device_count is None or platform is None:
        import jax

        devs = jax.devices()
        if device_count is None:
            device_count = len(devs)
        if platform is None:
            platform = jax.default_backend()
    if device_count <= 1:
        return None
    if platform == "cpu":
        return (f"multi-device XLA cpu mesh ({device_count} devices): "
                f"cache-served executables are nondeterministic on this "
                f"jax — AOT bundle serving needs a single-device or "
                f"TPU/GPU mesh")
    return None


def backend_supports_aot_serving(device_count: Optional[int] = None,
                                 platform: Optional[str] = None) -> bool:
    return aot_serving_reason(device_count, platform) is None

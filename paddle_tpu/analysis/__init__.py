"""Static analysis over lowered programs and paddle_tpu sources.

Two analyzers live here:

- **Program contracts** (:mod:`.contracts`, :mod:`.passes`,
  :mod:`.manager`): declarative statements of what a compiled executable
  must look like — collective counts/kinds, scan-loop survival, donation
  coverage, grad-comm payload dtype, host-transfer and constant hygiene,
  recompile hazards in the traced signature — checked by a pass manager
  over the HLO text and memory analysis of any executable. Both engines
  expose ``engine.analyze()``; ``tools/hlo_lint.py`` is the CLI.
- **Tracing-hazard source linter** (:mod:`.source_lint`): AST rules for
  the hazards jit hides until production — host syncs on traced values,
  wall-clock/``random`` inside traced code, mutable default args in
  public APIs, bare lock acquisition in the threaded subsystems — run
  repo-wide in tier-1 against a burned-down baseline;
  ``tools/lint_tracing.py`` is the CLI.
"""
from .backend import (backend_combines_collectives, backend_keeps_bf16_on_wire,
                      collective_combining_reason,
                      native_bf16_collective_reason)
from .contracts import (COLLECTIVE_KINDS, AnalysisReport, CountBound,
                        ProgramContract, Skip, Violation, check_bound)
from .manager import PassManager, check_compiled, check_text
from .passes import PASSES
from .program import Program, programs_from_stash

__all__ = [
    "AnalysisReport",
    "COLLECTIVE_KINDS",
    "CountBound",
    "PASSES",
    "PassManager",
    "Program",
    "ProgramContract",
    "Skip",
    "Violation",
    "backend_combines_collectives",
    "backend_keeps_bf16_on_wire",
    "check_bound",
    "check_compiled",
    "check_text",
    "collective_combining_reason",
    "native_bf16_collective_reason",
    "programs_from_stash",
]

"""The analysis pass suite.

Each pass is a function ``(program, contract) -> (violations, skips)``
registered in :data:`PASSES` under a stable name. Passes only check what
the contract declares (undeclared fields are free), so one suite serves
both strict perf gates and loose hygiene sweeps.

Pass inventory:

=================== =========================================================
collective-contract collective-op counts per kind + while-loop count, with
                    the backend-combining probe turning count checks into
                    skips on non-combining (CPU) pipelines
donation-leak       input state eligible for aliasing but not donated, via
                    the compiled memory analysis' alias bytes
dtype-upcast        f32 payloads on reduction collectives inside a declared
                    bf16/int8 gradient-communication region
host-transfer       infeed/outfeed/send/recv or host-callback custom-calls
                    inside a step program
constant-bloat      literals above max_constant_bytes baked into the HLO
recompile-hazard    weak-type / Python-scalar leaks in the traced signature
schedule-order      declared schedule disciplines read from the scheduled
                    module text; "all-gather-ahead" proves the fsdp gather
                    window moved each bucket's all-gather ahead of the
                    previous bucket's compute
=================== =========================================================
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

from .backend import (collective_combining_reason,
                      native_bf16_collective_reason)
from .contracts import (COLLECTIVE_KINDS, ProgramContract, Skip, Violation,
                        check_bound)
from .program import Program

PassResult = Tuple[List[Violation], List[Skip]]
PassFn = Callable[[Program, ProgramContract], PassResult]

#: collectives that REDUCE gradient payloads — the ops whose payload dtype
#: the comm_dtype contract governs. all-gather is exempt: ZeRO legitimately
#: gathers f32 updated params even when gradients travel as bf16/int8.
_REDUCTION_KINDS = ("all-reduce", "reduce-scatter")


def collective_contract(prog: Program, c: ProgramContract) -> PassResult:
    name = "collective-contract"
    if c.collectives is None and c.while_loops is None:
        return [], []
    if c.requires_combining:
        reason = collective_combining_reason()
        if reason is not None:
            return [], [Skip(prog.label, name, reason)]
    vs: List[Violation] = []
    for kind, bound in (c.collectives or {}).items():
        n = prog.count_ops(kind)
        want = check_bound(n, bound)
        if want is not None:
            vs.append(Violation(
                prog.label, name,
                f"{n} {kind} op(s), contract wants {want}"))
    want = check_bound(prog.count_while_loops(), c.while_loops)
    if want is not None:
        vs.append(Violation(
            prog.label, name,
            f"{prog.count_while_loops()} while loop(s), contract wants "
            f"{want} — scan fusion broken"))
    return vs, []


def donation_leak(prog: Program, c: ProgramContract) -> PassResult:
    name = "donation-leak"
    if not c.donated_bytes:
        return [], []
    mem = prog.memory_analysis()
    if mem is None or not hasattr(mem, "alias_size_in_bytes"):
        return [], [Skip(prog.label, name,
                         "backend exposes no alias/memory analysis")]
    aliased = int(mem.alias_size_in_bytes)
    need = int(c.donated_fraction * c.donated_bytes)
    if aliased >= need:
        return [], []
    return [Violation(
        prog.label, name,
        f"only {aliased} of {c.donated_bytes} eligible input-state bytes "
        f"are donation-aliased (need >= {need}); pass donate=True "
        f"or add donate_argnums")], []


def dtype_upcast(prog: Program, c: ProgramContract) -> PassResult:
    name = "dtype-upcast"
    if c.comm_dtype in (None, "f32", "float32"):
        return [], []
    if c.comm_dtype in ("bf16", "bfloat16") and not c.comm_dtype_strict:
        # CPU float normalization rewrites the bf16 psum to an f32
        # all-reduce — every declared-bf16 program would "violate" here
        # regardless of its source. Probe once; skip where the wire can't
        # carry bf16 (same design as requires_combining).
        reason = native_bf16_collective_reason()
        if reason is not None:
            return [], [Skip(prog.label, name, reason)]
    vs: List[Violation] = []
    for kind in _REDUCTION_KINDS:
        for line in prog.op_def_lines(kind):
            bad = [e for dt, e in prog.result_shapes(line)
                   if dt in ("f32", "f64") and e >= c.comm_min_elems]
            if bad:
                vs.append(Violation(
                    prog.label, name,
                    f"f32 payload ({max(bad)} elems) on a {kind} in a "
                    f"declared-{c.comm_dtype} grad-comm region: "
                    f"{line.strip()[:120]}"))
    return vs, []


def host_transfer(prog: Program, c: ProgramContract) -> PassResult:
    name = "host-transfer"
    if c.allow_host_calls:
        return [], []
    vs = [Violation(prog.label, name,
                    f"host transfer inside step program: {ln[:120]}")
          for ln in prog.host_transfer_lines()]
    return vs, []


def constant_bloat(prog: Program, c: ProgramContract) -> PassResult:
    name = "constant-bloat"
    if c.max_constant_bytes is None:
        return [], []
    vs: List[Violation] = []
    for dt, nbytes, line in prog.constants():
        if nbytes > c.max_constant_bytes:
            vs.append(Violation(
                prog.label, name,
                f"{nbytes}-byte {dt} literal baked into HLO (limit "
                f"{c.max_constant_bytes}); pass it as an argument instead: "
                f"{line[:80]}"))
    return vs, []


def recompile_hazard(prog: Program, c: ProgramContract) -> PassResult:
    name = "recompile-hazard"
    if prog.avals is None:
        return [], []
    vs: List[Violation] = []
    for i, a in enumerate(prog.avals):
        if isinstance(a, (bool, int, float, complex, str)):
            vs.append(Violation(
                prog.label, name,
                f"traced arg {i} is a Python scalar {a!r}: every distinct "
                f"value recompiles — pass a jnp array instead"))
        elif getattr(a, "weak_type", False):
            vs.append(Violation(
                prog.label, name,
                f"traced arg {i} ({getattr(a, 'dtype', '?')}"
                f"{list(getattr(a, 'shape', ()))}) is weakly typed: mixing "
                f"with a strong dtype retraces — cast explicitly at the "
                f"boundary"))
    return vs, []


# all-gather DEFINITION lines with their instruction name captured; async
# `-done` halves complete the matching `-start` and define no new gather
_AG_DEF_RE = re.compile(r"^\s*(%?all-gather(?!-done)[-.\w]*)\s*=")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")


def _first_consumer(lines: List[str], start: int, name: str,
                    ) -> Tuple[Optional[int], Optional[str]]:
    """(line index, kind) of the dominant consumer of instruction `name`:
    the first line after `start` (within the same computation — names are
    scoped) that takes %name as an operand and is a fusion/dot, falling
    back to the first consumer of any kind. Kind is "dominant" or "plain"
    or None when nothing consumes it before the computation closes."""
    tok = re.compile(re.escape(name if name.startswith("%") else "%" + name)
                     + r"(?![-.\w])")
    fallback = None
    for j in range(start + 1, len(lines)):
        if lines[j].startswith("}"):
            break
        if not tok.search(lines[j]):
            continue
        if " fusion(" in lines[j] or " dot(" in lines[j] \
                or " convolution(" in lines[j]:
            return j, "dominant"
        if fallback is None:
            fallback = j
    return (fallback, None if fallback is None else "plain")


def schedule_order(prog: Program, c: ProgramContract) -> PassResult:
    name = "schedule-order"
    if c.schedule_order is None:
        return [], []
    if c.schedule_order != "all-gather-ahead":
        return [Violation(
            prog.label, name,
            f"unknown schedule_order discipline {c.schedule_order!r} "
            f"(known: 'all-gather-ahead')")], []
    reason = collective_combining_reason()
    if reason is None:
        return [], [Skip(
            prog.label, name,
            "backend combines collectives: per-bucket all-gathers are "
            "fused, bucket schedule order is unreadable")]
    # jax-compiled modules are is_scheduled=true, so definition order in
    # the optimized text IS the execution schedule. Bucket order follows
    # channel ids (assigned in emission = bucket order) when present.
    lines = prog.hlo_text.splitlines()
    ags = []
    for i, ln in enumerate(lines):
        m = _AG_DEF_RE.match(ln)
        if m:
            ch = _CHANNEL_RE.search(ln)
            ags.append((int(ch.group(1)) if ch else len(ags),
                        i, m.group(1).strip()))
    ags.sort(key=lambda t: (t[0], t[1]))
    vs: List[Violation] = []
    for (_, li, ni), (_, lj, nj) in zip(ags, ags[1:]):
        ci, kind = _first_consumer(lines, li, ni)
        if ci is None:
            continue
        if lj >= ci:
            vs.append(Violation(
                prog.label, name,
                f"{nj} is defined at line {lj + 1}, after bucket "
                f"predecessor {ni}'s {kind or ''} consumer at line "
                f"{ci + 1} — gathers sit just-in-time, the prefetch "
                f"window did not move them ahead"))
    return vs, []


#: pass name -> pass fn, in report order
PASSES: Dict[str, PassFn] = {
    "collective-contract": collective_contract,
    "donation-leak": donation_leak,
    "dtype-upcast": dtype_upcast,
    "host-transfer": host_transfer,
    "constant-bloat": constant_bloat,
    "recompile-hazard": recompile_hazard,
    "schedule-order": schedule_order,
}

"""AST source linter for tracing hazards in paddle_tpu code.

jit makes certain Python idioms silently catastrophic: a ``float(x)`` on a
traced value blocks dispatch on a device→host sync (or fails under AOT), a
``time.time()`` inside a traced body freezes one wall-clock reading into
the compiled program forever, ``random.random()`` bakes a single "random"
constant, a mutable default arg aliases state across calls of a public
API, and a bare ``lock.acquire()`` in the threaded subsystems leaks the
lock on any exception path. None of these crash in tests; all of them
corrupt production. This linter encodes them as AST rules:

=============== ==========================================================
host-sync       ``float(x)``/``int(x)``/``bool(x)`` on a non-literal,
                ``.item()``/``.tolist()``, ``np.asarray``/``np.array`` —
                inside a traced (jitted/shard_mapped/scanned) body
host-time       ``time.time()``/``perf_counter()``/``datetime.now()``
                inside a traced body
host-random     Python ``random.*`` or ``np.random.*`` (not ``jax.random``)
                inside a traced body
mutable-default ``def f(x, acc=[])`` / ``={}`` / ``=set()`` in any public
                function (all files, not just traced code)
bare-lock       ``lock.acquire()`` outside a ``with`` statement (all files)
=============== ==========================================================

Tracedness is syntactic: a function is traced when it is decorated with
``jit``/``shard_map``/``partial(jax.jit, ...)`` or its *name* is passed to
a tracing entry point (``jax.jit(f)``, ``lax.scan(body, ...)``,
``grad``/``vmap``/``checkpoint``/``while_loop``/``cond``...), and every
function nested inside a traced one is traced too. That under-approximates
dynamically traced code and over-approximates dead branches — both are
what a linter should do; deliberate keeps go in the baseline with a
justification.

Baseline format (``tools/lint_tracing_baseline.txt``): one
``relpath:rule:qualname:token`` key per line, optional ``# justification``
after it. The comparison is burned-down in both directions: a finding not
in the baseline fails, and a baseline entry no longer found fails too
(delete it — the debt is paid).
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

#: final attribute names that trace their function-valued arguments
_TRACE_ENTRIES = {
    "jit", "shard_map", "scan", "grad", "value_and_grad", "vmap", "pmap",
    "checkpoint", "remat", "while_loop", "fori_loop", "cond", "switch",
    "custom_vjp", "custom_jvp", "eval_shape", "make_jaxpr", "xmap",
    "associative_scan", "capture_jit",
}
_HOST_TIME_CALLS = {"time", "perf_counter", "monotonic", "process_time",
                    "now", "utcnow", "time_ns", "perf_counter_ns"}
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist"}
_NP_SYNC_FUNCS = {"asarray", "array"}
_MUTABLE_LITERALS = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp)
_MUTABLE_CTORS = {"list", "dict", "set", "defaultdict", "OrderedDict"}


@dataclass
class Finding:
    path: str       # repo-relative
    line: int
    rule: str
    qualname: str   # enclosing function ("a.b.<locals>.c" style, or <module>)
    token: str      # the offending callee/arg, for a stable baseline key
    message: str

    @property
    def key(self) -> str:
        """Line-number-free identity — survives unrelated edits above it."""
        return f"{self.path}:{self.rule}:{self.qualname}:{self.token}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] {self.qualname}: "
                f"{self.message}")


def _attr_chain(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _callee(call: ast.Call) -> str:
    return _attr_chain(call.func)


def _is_partial_of_tracer(call: ast.Call) -> bool:
    """partial(jax.jit, ...) / functools.partial(shard_map, ...)."""
    if _callee(call).split(".")[-1] != "partial" or not call.args:
        return False
    return _attr_chain(call.args[0]).split(".")[-1] in _TRACE_ENTRIES


class _FileLinter(ast.NodeVisitor):
    def __init__(self, relpath: str, public_api: bool):
        self.relpath = relpath
        self.public_api = public_api
        self.findings: List[Finding] = []
        self.traced_names: Set[str] = set()
        self._stack: List[str] = []          # qualname parts
        self._traced_depth = 0               # >0 → inside a traced body
        self._with_calls: Set[ast.Call] = set()

    # -- sweep 1: which local functions get traced? -------------------------
    # Traced names are collected PER ENCLOSING SCOPE as "scope::name": the
    # inner `step` closure a _build method hands to jax.jit must not mark a
    # same-named public `step` method on the class as traced.
    def collect_traced(self, tree: ast.AST) -> None:
        def walk(node: ast.AST, scope: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    walk(child, f"{scope}.{child.name}" if scope
                         else child.name)
                    continue
                if isinstance(child, ast.Call) and \
                        _callee(child).split(".")[-1] in _TRACE_ENTRIES:
                    for arg in list(child.args) + [kw.value
                                                   for kw in child.keywords]:
                        nm = _attr_chain(arg)
                        if nm and "." not in nm:
                            self.traced_names.add(f"{scope}::{nm}")
                walk(child, scope)

        walk(tree, "")

    # -- sweep 2: walk, tracking qualname + tracedness ----------------------
    def _qual(self) -> str:
        return ".".join(self._stack) or "<module>"

    def _emit(self, node: ast.AST, rule: str, token: str, msg: str) -> None:
        self.findings.append(Finding(
            self.relpath, getattr(node, "lineno", 0), rule, self._qual(),
            token, msg))

    def _decorated_traced(self, node) -> bool:
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                if (_callee(dec).split(".")[-1] in _TRACE_ENTRIES
                        or _is_partial_of_tracer(dec)):
                    return True
            elif _attr_chain(dec).split(".")[-1] in _TRACE_ENTRIES:
                return True
        return False

    def _visit_func(self, node) -> None:
        traced = (self._decorated_traced(node)
                  or f"{'.'.join(self._stack)}::{node.name}"
                  in self.traced_names
                  or self._traced_depth > 0)
        if self.public_api and not node.name.startswith("_"):
            self._check_defaults(node)
        self._stack.append(node.name)
        if traced:
            self._traced_depth += 1
        self.generic_visit(node)
        if traced:
            self._traced_depth -= 1
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # lambdas passed to tracers are traced; approximating: a lambda in an
        # already-traced scope keeps the scope's tracedness (generic_visit).
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        args = node.args
        for a, d in list(zip(args.args[::-1], args.defaults[::-1])) + \
                list(zip(args.kwonlyargs, args.kw_defaults)):
            if d is None:
                continue
            mutable = isinstance(d, _MUTABLE_LITERALS) or (
                isinstance(d, ast.Call)
                and _callee(d).split(".")[-1] in _MUTABLE_CTORS)
            if mutable:
                self.findings.append(Finding(
                    self.relpath, node.lineno, "mutable-default",
                    ".".join(self._stack + [node.name]) or node.name, a.arg,
                    f"public API {node.name!r} has mutable default for "
                    f"{a.arg!r} — shared across calls; use None + init"))

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self._with_calls.add(item.context_expr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee = _callee(node)
        # the method name survives even when the receiver is a call result
        # (x.mean().item() has no Name root, so the attr chain is empty)
        last = (node.func.attr if isinstance(node.func, ast.Attribute)
                else callee.split(".")[-1])

        # bare-lock: anywhere, any file
        if last == "acquire" and node not in self._with_calls \
                and isinstance(node.func, ast.Attribute):
            self._emit(node, "bare-lock", _attr_chain(node.func),
                       f"bare {callee}() — leaks the lock on exception; "
                       f"use `with`")

        if self._traced_depth > 0:
            self._check_traced_call(node, callee, last)
        self.generic_visit(node)

    def _check_traced_call(self, node: ast.Call, callee: str,
                           last: str) -> None:
        # host-sync: float(x)/int(x)/bool(x) on non-literals, .item(), np.*
        if callee in _SYNC_BUILTINS and node.args and not isinstance(
                node.args[0], ast.Constant):
            self._emit(node, "host-sync", callee,
                       f"{callee}() on a traced value forces a device→host "
                       f"sync (and fails under AOT); keep it in jnp")
        elif last in _SYNC_METHODS and isinstance(node.func, ast.Attribute):
            self._emit(node, "host-sync", "." + last,
                       f".{last}() inside a traced body syncs to host")
        elif last in _NP_SYNC_FUNCS and callee.split(".")[0] in (
                "np", "numpy", "onp"):
            self._emit(node, "host-sync", callee,
                       f"{callee}() materializes a traced value on host; "
                       f"use jnp")
        # host-time
        elif last in _HOST_TIME_CALLS and callee.split(".")[0] in (
                "time", "datetime"):
            self._emit(node, "host-time", callee,
                       f"{callee}() in a traced body compiles to a frozen "
                       f"constant; time outside jit")
        # host-random (python/numpy RNG; jax.random is fine)
        elif callee.split(".")[0] == "random" or callee.startswith(
                ("np.random.", "numpy.random.", "onp.random.")):
            self._emit(node, "host-random", callee,
                       f"{callee}() in a traced body bakes one sample into "
                       f"the program; thread a jax.random key")


def lint_source(src: str, relpath: str,
                public_api: Optional[bool] = None) -> List[Finding]:
    """Lint one file's source. public_api defaults to 'is a library file'
    (paddle_tpu/*, not tests/tools)."""
    if public_api is None:
        public_api = relpath.startswith("paddle_tpu")
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 0, "parse-error", "<module>",
                        "syntax", f"cannot parse: {e.msg}")]
    linter = _FileLinter(relpath, public_api)
    linter.collect_traced(tree)
    linter.visit(tree)
    return linter.findings


def lint_tree(root: str,
              subdirs: Tuple[str, ...] = ("paddle_tpu", "tools"),
              ) -> List[Finding]:
    """Lint every .py under root/{subdirs}, sorted by (path, line)."""
    findings: List[Finding] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                with open(path, "r", encoding="utf-8") as f:
                    findings.extend(lint_source(f.read(), rel))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---- baseline -------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, str]:
    """key -> justification. Missing file = empty baseline."""
    out: Dict[str, str] = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            key, _, just = line.partition("#")
            out[key.strip()] = just.strip()
    return out


def compare_to_baseline(findings: List[Finding], baseline: Dict[str, str],
                        ) -> Tuple[List[Finding], List[str]]:
    """(new findings not in baseline, stale baseline keys no longer found).

    Both directions fail: new debt must be fixed or justified, paid-off
    debt must be deleted from the baseline — that's what keeps it burned
    DOWN rather than append-only.
    """
    current = {f.key for f in findings}
    new = [f for f in findings if f.key not in baseline]
    stale = sorted(k for k in baseline if k not in current)
    return new, stale

"""The analyzable unit: one lowered program + its HLO text, parsed lazily.

A :class:`Program` wraps whatever is available about one executable —
a compiled object (``jax.jit(f).lower(...).compile()``), raw optimized-HLO
text, the abstract call signature the engines stash for
``introspect_executables()``, or a (fn, avals) pair that can produce all of
the above on demand. Passes ask for what they need (`hlo_text`,
`memory_analysis`, `avals`) and the expensive steps (AOT compile) happen at
most once per program.

HLO parsing here deliberately matches the counting semantics the perf-gate
tests established (op DEFINITIONS by LHS instruction name, `) while(` for
loop count) so migrating a hand-written gate onto a contract cannot change
its verdict.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

# op definition lines: `%all-reduce.5 = (f32[...]) all-reduce(...)`.
# XLA names instructions after their opcode; `-done` halves of async pairs
# are completions of the matching `-start`, not extra collectives.
def _op_def_re(kind: str) -> "re.Pattern[str]":
    return re.compile(rf"^\s*%?{re.escape(kind)}(?!-done)[-.\w]*\s*=",
                      re.MULTILINE)


_WHILE_RE = re.compile(r"\) while\(")
_CONST_RE = re.compile(
    r"^\s*%?constant[-.\w]*\s*=\s*([a-z]+[0-9]*)\[([\d,]*)\]")
_SHAPE_GROUP_RE = re.compile(r"(bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32|s64|"
                             r"u64|pred|c64|c128)\[([\d,]*)\]")

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "f16": 2, "bf16": 2, "s16": 2,
                "u16": 2, "f32": 4, "s32": 4, "u32": 4, "c64": 8, "f64": 8,
                "s64": 8, "u64": 8, "c128": 16}

#: custom-call targets that bounce through the host (python callbacks); TPU
#: kernel custom-calls (tpu_custom_call, Mosaic) are NOT host transfers
_HOST_CALLBACK_MARKERS = ("callback", "host")
_HOST_OP_KINDS = ("infeed", "outfeed", "send", "recv")


def _elems(csv: str) -> int:
    n = 1
    for d in csv.split(","):
        if d:
            n *= int(d)
    return n


class Program:
    """One executable under analysis. Construct with whichever artifacts
    exist; the rest is derived lazily (and at most once)."""

    def __init__(self, label: str, compiled: Any = None,
                 hlo_text: Optional[str] = None, avals: Any = None,
                 lower_thunk: Any = None):
        self.label = label
        self.avals = avals
        self._compiled = compiled
        self._hlo_text = hlo_text
        self._lower_thunk = lower_thunk
        self._mem = _UNSET

    @classmethod
    def from_stash(cls, label: str, fn: Any, avals: Any) -> "Program":
        """From an engine's ``_exec_stash`` entry: AOT ``lower().compile()``
        deferred until a pass first needs the HLO (one compile per label)."""
        flat = _flatten(avals)
        return cls(label, avals=flat,
                   lower_thunk=lambda: fn.lower(*avals).compile())

    @property
    def compiled(self) -> Any:
        if self._compiled is None and self._lower_thunk is not None:
            self._compiled = self._lower_thunk()
        return self._compiled

    @property
    def hlo_text(self) -> str:
        if self._hlo_text is None:
            comp = self.compiled
            if comp is None:
                raise ValueError(
                    f"program {self.label!r} has neither HLO text nor a "
                    f"compiled executable to read it from")
            self._hlo_text = comp.as_text()
        return self._hlo_text

    def memory_analysis(self) -> Any:
        """compiled.memory_analysis() or None (text-only programs, backends
        without PJRT memory stats)."""
        if self._mem is _UNSET:
            try:
                comp = self.compiled
                self._mem = None if comp is None else comp.memory_analysis()
            except Exception:
                self._mem = None
        return self._mem

    # ---- HLO queries -------------------------------------------------------
    def count_ops(self, kind: str) -> int:
        """Op DEFINITIONS of `kind` (LHS instruction name match — the exact
        semantics of the perf-gate regexes this layer replaces)."""
        return len(_op_def_re(kind).findall(self.hlo_text))

    def op_def_lines(self, kind: str) -> List[str]:
        pat = _op_def_re(kind)
        return [ln for ln in self.hlo_text.splitlines() if pat.match(ln)]

    def count_while_loops(self) -> int:
        return len(_WHILE_RE.findall(self.hlo_text))

    def constants(self) -> List[Tuple[str, int, str]]:
        """(dtype, bytes, line) per `constant` op definition."""
        out = []
        for ln in self.hlo_text.splitlines():
            m = _CONST_RE.match(ln)
            if m:
                dt, csv = m.group(1), m.group(2)
                out.append((dt, _elems(csv) * _DTYPE_BYTES.get(dt, 4),
                            ln.strip()))
        return out

    def host_transfer_lines(self) -> List[str]:
        """infeed/outfeed/send/recv op definitions plus custom-calls whose
        target names a host (python) callback."""
        out = []
        kinds = [(_op_def_re(k), None) for k in _HOST_OP_KINDS]
        cc = _op_def_re("custom-call")
        for ln in self.hlo_text.splitlines():
            if cc.match(ln):
                m = re.search(r'custom_call_target="([^"]*)"', ln)
                tgt = (m.group(1) if m else "").lower()
                if any(mark in tgt for mark in _HOST_CALLBACK_MARKERS):
                    out.append(ln.strip())
                continue
            for pat, _ in kinds:
                if pat.match(ln):
                    out.append(ln.strip())
                    break
        return out

    def result_shapes(self, line: str) -> List[Tuple[str, int]]:
        """(dtype, element-count) for every typed shape mentioned on an op
        line (result + operands — operand dtypes equal their defs')."""
        return [(dt, _elems(csv))
                for dt, csv in _SHAPE_GROUP_RE.findall(line)]


_UNSET = object()


def _flatten(avals) -> List[Any]:
    """Leaves of the stash's aval tree (jax optional: avals may be plain)."""
    try:
        import jax

        return list(jax.tree_util.tree_leaves(avals))
    except Exception:
        return [avals]


def programs_from_stash(stash: Dict[str, Any]) -> List[Program]:
    """One lazy Program per engine ``_exec_stash`` entry."""
    return [Program.from_stash(label, fn, avals)
            for label, (fn, avals) in sorted(stash.items())]

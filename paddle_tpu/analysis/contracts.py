"""Declarative program contracts + the analysis result model.

A :class:`ProgramContract` states what a lowered program is SUPPOSED to look
like — how many collectives of which kind, how many scan loops, how many
bytes donation must alias, which payload dtype the gradient collectives
carry, whether host transfers are tolerated — as plain data. The pass suite
in ``analysis/passes.py`` turns each declared field into checks; fields left
``None`` are simply unchecked, so a contract can be as tight (a perf gate
pinning "exactly one reduce-scatter") or as loose (hygiene-only: no host
callbacks, no constant bloat) as the program warrants.

This replaces the hand-written ``re.findall`` gates that grew across
tests/test_hlo_perf_gates.py, test_zero_update.py and test_health.py: the
same counting semantics, declared once, reusable from ``engine.analyze()``,
``tools/hlo_lint.py`` and the tests.
"""
from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple, Union

# a count bound: exact int, (lo, hi) inclusive range ((lo, None) = no upper
# bound), or None = unchecked
CountBound = Union[int, Tuple[int, Optional[int]], None]

#: collective op kinds the contract language knows about (HLO opcode names)
COLLECTIVE_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
                    "all-to-all", "collective-permute")


def check_bound(n: int, bound: CountBound) -> Optional[str]:
    """None when `n` satisfies `bound`, else a human-readable description
    of the expectation ("exactly 1", "in [1, 4]", ">= 5"). A (lo, None)
    tuple is open-ended above."""
    if bound is None:
        return None
    if isinstance(bound, int):
        return None if n == bound else f"exactly {bound}"
    lo, hi = bound
    if hi is None:
        return None if n >= lo else f">= {lo}"
    return None if lo <= n <= hi else f"in [{lo}, {hi}]"


@dataclass
class ProgramContract:
    """What one executable (or a label family) promises.

    label: fnmatch pattern over executable labels ("train.zero_*").
    collectives: kind -> CountBound over COLLECTIVE_KINDS op definitions.
    requires_combining: the collective counts only hold on backends that run
        XLA's AllReduceCombiner (TPU/GPU); elsewhere the collective checks
        are reported as skips, not violations — the shared predicate behind
        the 4 probe-skipped perf gates (analysis/backend.py).
    while_loops: CountBound on compiled `while(` loops (scan survival).
    donated_bytes: bytes of input state eligible for aliasing; the
        donation-leak pass requires alias_size >= donated_fraction * this.
    comm_dtype: declared gradient-collective payload dtype (f32|bf16|int8);
        bf16/int8 forbid f32 reduction collectives above comm_min_elems.
    comm_dtype_strict: by default a declared-bf16 contract is SKIPPED on
        backends whose float normalization legalizes bf16 collectives to
        f32 on the wire (this CPU pipeline) — the compiled program shows
        f32 payloads no matter what the source did, so the check cannot
        separate a source-level upcast bug from backend legalization.
        True forces the check regardless (seeded-violation fixtures).
    allow_host_calls: when False, infeed/outfeed/send/recv and host-callback
        custom-calls in the program are violations.
    max_constant_bytes: largest literal that may be baked into the program
        (None disables the constant-bloat check).
    schedule_order: declared schedule discipline read from the SCHEDULED
        optimized-HLO text (the modules jax compiles are is_scheduled, so
        definition order IS execution order). The one discipline today is
        "all-gather-ahead" (the fsdp gather-prefetch window): each bucket's
        all-gather definition must precede the previous bucket's dominant
        dot/fusion consumer — the CPU-checkable proof that the prefetch
        actually moved the gathers ahead of the compute that hides them.
        Skipped on combining backends (per-bucket gathers get fused there,
        so bucket order is unreadable). None = unchecked.
    """

    label: str = "*"
    collectives: Optional[Dict[str, CountBound]] = None
    requires_combining: bool = False
    while_loops: CountBound = None
    donated_bytes: Optional[int] = None
    donated_fraction: float = 0.9
    comm_dtype: Optional[str] = None
    comm_dtype_strict: bool = False
    comm_min_elems: int = 64
    allow_host_calls: bool = False
    max_constant_bytes: Optional[int] = 2 * 1024 * 1024
    schedule_order: Optional[str] = None
    name: str = ""  # optional display name for reports

    def matches(self, label: str) -> bool:
        return fnmatch.fnmatchcase(label, self.label)


@dataclass
class Violation:
    label: str
    pass_name: str
    message: str

    def __str__(self) -> str:
        return f"[{self.pass_name}] {self.label}: {self.message}"


@dataclass
class Skip:
    label: str
    pass_name: str
    reason: str

    def __str__(self) -> str:
        return f"[{self.pass_name}] {self.label}: skipped — {self.reason}"


@dataclass
class AnalysisReport:
    """What one PassManager.run saw: which labels were checked, every
    violation, and every backend-capability skip."""

    violations: List[Violation] = field(default_factory=list)
    skips: List[Skip] = field(default_factory=list)
    checked: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def for_label(self, label: str) -> List[Violation]:
        return [v for v in self.violations if v.label == label]

    def summary(self) -> Dict[str, Any]:
        return {
            "ok": self.ok,
            "checked": sorted(set(self.checked)),
            "violations": [
                {"label": v.label, "pass": v.pass_name, "message": v.message}
                for v in self.violations],
            "skips": [
                {"label": s.label, "pass": s.pass_name, "reason": s.reason}
                for s in self.skips],
        }

    def format(self) -> str:
        lines = [f"analyzed {len(set(self.checked))} executable(s): "
                 f"{len(self.violations)} violation(s), "
                 f"{len(self.skips)} skip(s)"]
        lines += ["  VIOLATION " + str(v) for v in self.violations]
        lines += ["  skip " + str(s) for s in self.skips]
        return "\n".join(lines)

    __str__ = format

"""PassManager: run the pass suite over programs under contracts.

The manager is where results meet the observability stack: every violation
bumps ``analysis.violations`` (and ``analysis.violations.<pass>``) in both
the lightweight monitor stats and, when enabled, the metrics registry —
and with ``FLAGS_analysis_flight_dump`` set, a flight-recorder dump named
``analysis_<pass>_<label>`` captures the surrounding step records.

Entry points:

- ``PassManager().run(programs, contracts)`` — the general form.
- ``check_compiled(label, compiled, contract)`` — one AOT executable.
- ``check_text(label, hlo_text, contract)`` — raw HLO text (no donation /
  signature passes, which need the compiled object / avals).
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from .contracts import AnalysisReport, ProgramContract
from .passes import PASSES, PassFn
from .program import Program


class PassManager:
    """Runs a pass suite (default: all of :data:`PASSES`) over programs."""

    def __init__(self, passes: Optional[Dict[str, PassFn]] = None):
        self.passes: Dict[str, PassFn] = dict(passes or PASSES)

    def run(self, programs: Iterable[Program],
            contracts: Sequence[ProgramContract],
            dump: Optional[bool] = None) -> AnalysisReport:
        """Check every program against every contract whose label pattern
        matches it. `dump` overrides FLAGS_analysis_flight_dump."""
        report = AnalysisReport()
        for prog in programs:
            matched = [c for c in contracts if c.matches(prog.label)]
            if matched:
                report.checked.append(prog.label)
            for c in matched:
                for fn in self.passes.values():
                    vs, ss = fn(prog, c)
                    report.violations.extend(vs)
                    report.skips.extend(ss)
        _publish(report, dump=dump)
        return report


def _publish(report: AnalysisReport, dump: Optional[bool] = None) -> None:
    """Violation counters + optional flight dump. Never raises: analysis is
    diagnostics, it must not take down the path it watches."""
    if not report.violations:
        return
    try:
        from ..core import monitor

        monitor.stat("analysis.violations").increase(len(report.violations))
        for v in report.violations:
            monitor.stat(f"analysis.violations.{v.pass_name}").increase()
    except Exception:
        pass
    try:
        from ..observability import metrics

        reg = metrics.active_registry()
        if reg is not None:
            reg.counter("analysis.violations",
                        "program-contract violations").inc(
                            len(report.violations))
            for v in report.violations:
                reg.counter(f"analysis.violations.{v.pass_name}",
                            "violations by analysis pass").inc()
    except Exception:
        pass
    try:
        if dump is None:
            from ..core.flags import flag

            dump = bool(flag("analysis_flight_dump"))
        if dump:
            from ..observability import flight_recorder

            rec = flight_recorder.get()
            if rec is not None:
                v = report.violations[0]
                rec.dump(f"analysis_{v.pass_name}_{v.label}",
                         extra=report.summary())
    except Exception:
        pass


def check_compiled(label: str, compiled: Any,
                   contract: ProgramContract,
                   avals: Any = None) -> AnalysisReport:
    """Lint one already-compiled executable against one contract."""
    prog = Program(label, compiled=compiled,
                   avals=list(avals) if avals is not None else None)
    return PassManager().run([prog], [contract])


def check_text(label: str, hlo_text: str,
               contract: ProgramContract) -> AnalysisReport:
    """Lint raw optimized-HLO text (donation/signature passes will skip —
    they need the compiled object / traced avals)."""
    prog = Program(label, hlo_text=hlo_text)
    return PassManager().run([prog], [contract])

"""paddle.summary: layer-by-layer output shapes + parameter counts via forward hooks.
Reference: python/paddle/hapi/model_summary.py."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        multi = (isinstance(input_size, (list, tuple)) and len(input_size) > 0
                 and isinstance(input_size[0], (list, tuple)))
        sizes = list(input_size) if multi else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
        input = [Tensor(np.zeros([d if d and d > 0 else 1 for d in s],
                                 dtype=dt or "float32"))
                 for s, dt in zip(sizes, dts)]
    elif not isinstance(input, (list, tuple)):
        input = [input]

    rows, hooks = [], []

    def make_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (list, tuple)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else []
            n_params = sum(int(np.prod(p.shape)) for p in lyr.parameters(
                include_sublayers=False))
            rows.append((name or lyr.__class__.__name__, shape, n_params))
        return hook

    for name, sub in net.named_sublayers():
        if not list(sub.children()):  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))
    was_training = net.training
    net.eval()
    try:
        net(*input)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    w1 = max([len(r[0]) for r in rows] + [10]) + 2
    print(f"{'Layer':<{w1}}{'Output Shape':<24}{'Param #':>12}")
    print("=" * (w1 + 36))
    for name, shape, n in rows:
        print(f"{name:<{w1}}{str(shape):<24}{n:>12,}")
    print("=" * (w1 + 36))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    return {"total_params": total, "trainable_params": trainable}

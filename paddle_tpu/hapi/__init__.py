"""High-level API (hapi): Keras-like Model.prepare/fit/evaluate/predict.

Reference: python/paddle/hapi/model.py:907 (Model), :1557 (fit); callbacks at
python/paddle/hapi/callbacks.py. The reference wraps both dygraph and static graph
adapters; TPU-natively there is one adapter — the eager path, whose hot train step is
already a fused XLA computation via the optimizer/autograd stack.
"""
from .model import Model
from .callbacks import (Callback, CallbackList, EarlyStopping, LRScheduler,
                        ModelCheckpoint, ProgBarLogger, VisualDL)
from .summary import summary

__all__ = ["Model", "Callback", "CallbackList", "EarlyStopping", "LRScheduler",
           "ModelCheckpoint", "ProgBarLogger", "VisualDL", "summary"]

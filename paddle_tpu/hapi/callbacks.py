"""Callbacks for hapi Model.fit. Reference: python/paddle/hapi/callbacks.py
(config_callbacks, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping,
VisualDL)."""
from __future__ import annotations

import numbers
import os
import sys
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


def _fmt_logs(logs):
    parts = []
    for k, v in (logs or {}).items():
        if k in ("batch_size",):
            continue
        if isinstance(v, (list, tuple)):
            v = v[0] if len(v) == 1 else list(v)
        if isinstance(v, numbers.Number):
            parts.append(f"{k}: {v:.4f}")
        else:
            parts.append(f"{k}: {v}")
    return " - ".join(parts)


class ProgBarLogger(Callback):
    """Prints per-epoch progress: `step N/M - loss: x - acc: y - t/step`."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        if self.verbose:
            print("The loss value printed in the log is the current step, and the "
                  "metric is the average value of previous steps.", flush=True)

    def on_epoch_begin(self, epoch, logs=None):
        self.steps = self.params.get("steps")
        self.epoch = epoch
        self._t0 = time.time()
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}", flush=True)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and (step % self.log_freq == 0 or step + 1 == (self.steps or 0)):
            dt = (time.time() - self._t0) / max(1, step + 1)
            total = self.steps if self.steps is not None else "?"
            print(f"step {step + 1}/{total} - {_fmt_logs(logs)} - {dt * 1000:.0f}ms/step",
                  file=sys.stdout, flush=True)

    def on_eval_begin(self, logs=None):
        self.eval_steps = (logs or {}).get("steps")
        if self.verbose:
            print(f"Eval begin...", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval samples: {(logs or {}).get('samples', '?')} - {_fmt_logs(logs)}",
                  flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is None or self.save_dir is None:
            return
        if epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir is not None:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in monitor):
            self.monitor_op = lambda cur, best: cur < best - self.min_delta
            self.best_value = float("inf")
        else:
            self.monitor_op = lambda cur, best: cur > best + self.min_delta
            self.best_value = -float("inf")

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.monitor_op(current, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None \
                    and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir, "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            self.stopped_epoch = getattr(self, "_epoch", 0)
            if self.verbose:
                print(f"Epoch {self.stopped_epoch}: Early stopping.", flush=True)


class TelemetryCallback(Callback):
    """Per-step structured telemetry for Model.fit, emitting one
    observability.StepTelemetry JSONL record per train batch (wall time,
    samples/s, loss, tracked reader_cost, compile/dispatch counters).

    Wall time spans on_train_batch_begin -> end; train_batch syncs on the
    loss (float(item())) so the measurement is honest. Auto-attached by
    config_callbacks when PADDLE_TPU_TELEMETRY_DIR is set."""

    def __init__(self, telemetry=None, path=None, flops_per_token=None):
        super().__init__()
        if telemetry is None:
            from ..observability import InMemorySink, JsonlSink, StepTelemetry

            sink = JsonlSink(path) if path else InMemorySink()
            telemetry = StepTelemetry(sink=sink,
                                      flops_per_token=flops_per_token)
        self.telemetry = telemetry
        self._t0 = None
        self._step = 0

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        logs = logs or {}
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple)):
            loss = loss[0] if loss else None
        self._step += 1
        self.telemetry.record_step(
            step=self._step, wall_time=dt,
            samples=logs.get("batch_size"),
            loss=float(loss) if isinstance(loss, numbers.Number) else None,
            reader_cost=logs.get("reader_cost"))

    def on_train_end(self, logs=None):
        self.telemetry.close()


class VisualDL(Callback):
    """Scalar logging callback. The visualdl package is not available in this image;
    scalars are appended to a jsonl file the user can plot with any tool."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def _write(self, tag, logs):
        import json

        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"tag": tag, "step": self._step}
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)) and v and isinstance(v[0], numbers.Number):
                rec[k] = float(v[0])
            elif isinstance(v, numbers.Number):
                rec[k] = float(v)
        with open(os.path.join(self.log_dir, "scalars.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    tele_dir = os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
    if (tele_dir and mode == "train"
            and not any(isinstance(c, TelemetryCallback) for c in cbks)):
        cbks.append(TelemetryCallback(
            path=os.path.join(tele_dir, "fit_telemetry.jsonl")))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                    "verbose": verbose, "metrics": metrics or []})
    return lst

"""paddle.flops: per-layer FLOPs estimation via forward hooks.
Reference: python/paddle/hapi/dynamic_flops.py (op-type handler table driven by hooks)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer


def _numel(x):
    return int(np.prod(x.shape)) if len(x.shape) else 1


def _count_conv(layer, inputs, output):
    out = _numel(output)
    kernel_ops = int(np.prod(layer.kernel_size)) * (layer.in_channels // layer.groups)
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    return out * (kernel_ops + bias_ops)


def _count_linear(layer, inputs, output):
    mul = int(layer.weight.shape[0])
    bias_ops = 1 if getattr(layer, "bias", None) is not None else 0
    return _numel(output) // max(int(output.shape[-1]), 1) * (
        mul * int(output.shape[-1]) + bias_ops * int(output.shape[-1]))


def _count_norm(layer, inputs, output):
    return 2 * _numel(inputs[0])


def _count_act(layer, inputs, output):
    return _numel(output)


def _count_pool(layer, inputs, output):
    return _numel(output)


def _handlers():
    from .. import nn

    table = {}
    for cls_name, fn in [
        ("Conv1D", _count_conv), ("Conv2D", _count_conv), ("Conv3D", _count_conv),
        ("Linear", _count_linear),
        ("BatchNorm", _count_norm), ("BatchNorm1D", _count_norm),
        ("BatchNorm2D", _count_norm), ("BatchNorm3D", _count_norm),
        ("LayerNorm", _count_norm), ("GroupNorm", _count_norm),
        ("InstanceNorm2D", _count_norm), ("SyncBatchNorm", _count_norm),
        ("ReLU", _count_act), ("ReLU6", _count_act), ("GELU", _count_act),
        ("Sigmoid", _count_act), ("Tanh", _count_act), ("LeakyReLU", _count_act),
        ("Hardswish", _count_act), ("Hardsigmoid", _count_act), ("Swish", _count_act),
        ("AvgPool1D", _count_pool), ("AvgPool2D", _count_pool), ("AvgPool3D", _count_pool),
        ("MaxPool1D", _count_pool), ("MaxPool2D", _count_pool), ("MaxPool3D", _count_pool),
        ("AdaptiveAvgPool1D", _count_pool), ("AdaptiveAvgPool2D", _count_pool),
        ("AdaptiveMaxPool2D", _count_pool),
    ]:
        cls = getattr(nn, cls_name, None)
        if cls is not None:
            table[cls] = fn
    return table


def flops(net: Layer, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Return total FLOPs (multiply-adds counted once) for one forward pass."""
    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        inputs = [Tensor(np.zeros([d if d and d > 0 else 1 for d in input_size],
                                  dtype="float32"))]
    elif not isinstance(inputs, (list, tuple)):
        inputs = [inputs]

    table = _handlers()
    if custom_ops:
        table.update(custom_ops)
    rows, hooks = [], []

    def make_hook(name, layer, fn):
        def hook(lyr, ins, outs):
            out = outs[0] if isinstance(outs, (list, tuple)) else outs
            ins = ins if isinstance(ins, (list, tuple)) else (ins,)
            n = int(fn(lyr, ins, out))
            rows.append((name or lyr.__class__.__name__, n))
        return hook

    for name, sub in net.named_sublayers():
        fn = None
        for cls, handler in table.items():
            if isinstance(sub, cls):
                fn = handler
                break
        if fn is not None and not list(sub.children()):
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub, fn)))

    was_training = net.training
    net.eval()
    try:
        net(*inputs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(n for _, n in rows)
    if print_detail:
        w1 = max([len(r[0]) for r in rows] + [10]) + 2
        print(f"{'Layer':<{w1}}{'FLOPs':>16}")
        for name, n in rows:
            print(f"{name:<{w1}}{n:>16,}")
        print(f"Total FLOPs: {total:,}")
    return total

"""hapi Model: the Keras-like training facade.

Reference: python/paddle/hapi/model.py:907 (Model), :1486 (evaluate), :1557 (fit).
The reference dispatches to a DynamicGraphAdapter or StaticGraphAdapter; here the
eager engine is the single adapter — its loss.backward()/opt.step() path is already
one fused XLA computation, so there is nothing to gain from a separate static path.
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..metric import Metric
from .callbacks import config_callbacks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensors(xs):
    out = []
    for x in _to_list(xs):
        out.append(x if isinstance(x, Tensor) else Tensor(np.asarray(x)))
    return out


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._save_dir = None
        self.stop_training = False
        # fused gradient-accumulation engine (distributed/grad_comm.py):
        # built lazily by fit(accumulate_grad_batches=K) when the engine
        # path applies; None means the eager K-dispatch fallback is in use
        self._engine = None

    # ---- configuration ----
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        if loss is not None and not callable(loss):
            raise TypeError("loss must be callable (a loss Layer or function)")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m!r} is not a paddle.metric.Metric")
        self._amp_configs = amp_configs or {}
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    # ---- single-batch primitives ----
    # Donation audit: this eager path never donates — loss.backward() /
    # opt.step() mutate Parameter._data in place through the optimizer, so
    # no buffer a caller can hold is ever handed to XLA for aliasing. The
    # donated world is TrainStepEngine/auto_parallel.Engine, which rebind
    # engine.params before returning (tests/test_donation_safety.py pins
    # the boundary); fit() composes with either without reuse hazards.
    def train_batch(self, inputs, labels=None, update=True):
        assert self._optimizer is not None, "call prepare() with an optimizer first"
        self.network.train()
        inputs, labels = _to_tensors(inputs), _to_tensors(labels)
        outputs = _to_list(self.network(*inputs))
        losses = self._compute_loss(outputs, labels)
        total = losses[0]
        for extra in losses[1:]:
            total = total + extra
        accumulate = getattr(self, "_accumulate", 1)
        if accumulate > 1:
            # average grads over the accumulation window so the effective step
            # matches a single large batch (reference model.py scales final_loss)
            total = total * (1.0 / accumulate)
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        loss_vals = [float(l.item()) for l in losses]
        return (loss_vals, metrics) if metrics else loss_vals

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs, labels = _to_tensors(inputs), _to_tensors(labels)
        with no_grad():
            outputs = _to_list(self.network(*inputs))
            # loss=None with no metrics means the network computes its own loss;
            # loss=None with metrics means metrics-only evaluation
            losses = (self._compute_loss(outputs, labels)
                      if self._loss is not None or not self._metrics else [])
        metrics = self._update_metrics(outputs, labels)
        loss_vals = [float(l.item()) for l in losses]
        return (loss_vals, metrics) if metrics else loss_vals

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _to_tensors(inputs)
        with no_grad():
            outputs = _to_list(self.network(*inputs))
        return [o.numpy() for o in outputs]

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            # network returns the loss directly
            return [outputs[0]]
        return _to_list(self._loss(*(outputs + labels)))

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self._metrics:
            state = m.compute(*(outputs + labels))
            m.update(*[s.numpy() if isinstance(s, Tensor) else s for s in _to_list(state)])
            res = m.accumulate()
            vals.append(res)
        return vals

    # ---- fused gradient accumulation (engine path) ----
    def _accum_engine(self, k, n_inputs):
        """TrainStepEngine with microbatches=K for fit(accumulate_grad_
        batches=K): the K accumulation microbatches run inside ONE compiled
        dispatch with a single deferred fused gradient all-reduce
        (distributed/grad_comm.py), instead of K eager dispatches with K
        reductions. Applies when no metrics are configured (the engine
        returns only the loss); anything unsupported falls back to the
        eager K-dispatch path. Returns the engine or None."""
        if self._metrics or self._optimizer is None:
            return None
        try:
            from ..distributed.engine import TrainStepEngine

            # fresh engine per fit: it snapshots network weights at
            # construction, so reuse across fits would train stale params
            self._engine = TrainStepEngine(
                self.network, self._optimizer, loss_fn=self._loss,
                microbatches=k,
                num_model_inputs=n_inputs if self._loss is not None else None)
        except Exception:
            self._engine = None
        return self._engine

    def _engine_group_step(self, engine, group):
        """Concatenate K stashed (inputs, labels) loader batches along the
        batch dim and run them as one accumulated engine step."""
        import numpy as np

        k = len(group)
        cols = []
        for pos in range(len(group[0])):
            arrs = [np.asarray(b[pos].numpy() if isinstance(b[pos], Tensor)
                               else b[pos]) for b in group]
            cols.append(np.concatenate(arrs, axis=0))
        engine.microbatches = k
        loss = engine.step(*[Tensor(c) for c in cols])
        return [float(loss.item())]

    # ---- loops ----
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last=False,
                     prefetch_factor=2):
        from ..io import DataLoader, Dataset, IterableDataset

        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, (Dataset, IterableDataset)):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last,
                              prefetch_factor=prefetch_factor)
        # any other iterable of ready-made batches: materialize so a generator
        # survives re-iteration across epochs
        return data if hasattr(data, "__getitem__") else list(data)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, prefetch_factor=2):
        assert train_data is not None, "train_data must be given"
        self._save_dir = save_dir
        loader = self._make_loader(train_data, batch_size, shuffle, num_workers,
                                   drop_last, prefetch_factor=prefetch_factor)
        eval_loader = self._make_loader(eval_data, batch_size, False, num_workers)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        self._accumulate = max(1, accumulate_grad_batches)
        engine = None  # resolved at the first batch (needs the input count)
        cbks = config_callbacks(callbacks, model=self, epochs=epochs, steps=steps,
                                batch_size=batch_size, verbose=verbose,
                                log_freq=log_freq, save_freq=save_freq,
                                save_dir=save_dir, metrics=self._metrics_name())
        self.stop_training = False
        cbks.on_train_begin()
        history = []
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            pending_update = False
            group = []          # engine path: stashed microbatches
            group_reader = 0.0
            # manual iteration so the dataloader fetch is timed: reader_cost
            # rides in logs for ProgBar/telemetry and is what Benchmark's
            # step(reader_cost=...) hook receives instead of a fake 0.0.
            # With num_workers > 0 (or the default buffered reader) batch
            # production runs in background threads, so this measures the
            # RESIDUAL (non-overlapped) wait — near zero when the pipeline
            # keeps up — not the full fetch+collate cost.
            batches = iter(enumerate(loader))
            while True:
                t_fetch = time.perf_counter()
                try:
                    step, batch = next(batches)
                except StopIteration:
                    break
                reader_dt = time.perf_counter() - t_fetch
                if num_iters is not None and step >= num_iters:
                    break
                ins, labs = self._split_batch(batch)
                if self._accumulate > 1 and engine is None:
                    # one engine decision per fit: the fused K-microbatch
                    # dispatch (grad_comm) when it applies, else the eager
                    # K-dispatch accumulation below
                    engine = self._accum_engine(self._accumulate, len(ins)) \
                        or False
                if engine:
                    # engine path: stash K loader batches, then ONE compiled
                    # dispatch accumulates them with a single deferred
                    # gradient all-reduce. Callback cadence: begin per
                    # loader batch, end on the dispatching batch.
                    cbks.on_train_batch_begin(step)
                    group.append(ins + labs)
                    group_reader += reader_dt
                    if len(group) == self._accumulate:
                        out = self._engine_group_step(engine, group)
                        group, reader_sum = [], group_reader
                        group_reader = 0.0
                        logs = self._pack_logs(out, batch_size)
                        logs["reader_cost"] = reader_sum
                        cbks.on_train_batch_end(step, logs)
                    if self.stop_training:
                        break
                    continue
                cbks.on_train_batch_begin(step)
                update = (step + 1) % accumulate_grad_batches == 0
                out = self.train_batch(ins, labs, update=update)
                pending_update = not update
                logs = self._pack_logs(out, batch_size)
                logs["reader_cost"] = reader_dt
                cbks.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            if group:
                # engine-path tail: fewer than K batches left in the epoch —
                # run them as a shorter accumulation group (own compiled
                # variant, cached per K) so nothing leaks into the next epoch
                out = self._engine_group_step(engine, group)
                logs = self._pack_logs(out, batch_size)
                logs["reader_cost"] = group_reader
                cbks.on_train_batch_end(step, logs)
            if pending_update:
                # flush tail gradients when the epoch length is not divisible by
                # accumulate_grad_batches, so nothing leaks into the next epoch
                self._optimizer.step()
                self._optimizer.clear_grad()
            if engine:
                # eval / checkpoint callbacks read the eager network — write
                # the engine-owned params back at every epoch boundary
                engine.sync_to_model()
            if eval_loader is not None and epoch % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            history.append(logs)
        cbks.on_train_end(logs if history else {})
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, log_freq=log_freq,
                                metrics=self._metrics_name())
        return self._run_eval(loader, cbks, num_iters=num_iters)

    def _run_eval(self, loader, cbks, num_iters=None):
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({"steps": self._safe_len(loader)})
        logs, samples = {}, 0
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_eval_batch_begin(step)
            ins, labs = self._split_batch(batch)
            out = self.eval_batch(ins, labs)
            logs = self._pack_logs(out, None)
            samples += len(ins[0]) if ins and hasattr(ins[0], "__len__") else 0
            cbks.on_eval_batch_end(step, logs)
        logs["samples"] = samples
        cbks.on_eval_end(logs)
        logs.pop("samples", None)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose)
        cbks.on_predict_begin()
        outputs: List[List[np.ndarray]] = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cbks.on_predict_batch_end(step)
        cbks.on_predict_end()
        # transpose: list over batches of list over outputs -> list over outputs
        n_out = len(outputs[0]) if outputs else 0
        result = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        return result

    def _split_batch(self, batch, has_labels=True):
        batch = _to_list(batch)
        if self._inputs:
            n_in = len(self._inputs)
        elif self._loss is None and not self._metrics:
            # network computes its own loss from the full batch
            n_in = len(batch)
        elif len(batch) == 1:
            n_in = 1
        else:
            n_in = max(1, len(batch) - 1)
        return batch[:n_in], batch[n_in:] if has_labels else []

    def _pack_logs(self, out, batch_size):
        logs = {}
        if self._metrics:
            losses, metrics = out
        else:
            losses, metrics = out, []
        if losses:
            logs["loss"] = losses if len(losses) > 1 else losses[0]
        for m, v in zip(self._metrics, metrics):
            names = m.name() if isinstance(m.name(), (list, tuple)) else [m.name()]
            vals = v if isinstance(v, (list, tuple)) else [v]
            for n, val in zip(names, vals):
                logs[n] = val
        if batch_size:
            logs["batch_size"] = batch_size
        return logs

    @staticmethod
    def _safe_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, (list, tuple)) else [n])
        return names

    # ---- persistence ----
    def save(self, path, training=True):
        from ..framework import io as fio

        fio.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fio.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import io as fio

        state = fio.load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(fio.load(path + ".pdopt"))

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size, dtypes=dtype)

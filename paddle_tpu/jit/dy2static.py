"""Dygraph→static AST transpiler.

Reference: python/paddle/fluid/dygraph/dygraph_to_static/ — ProgramTranslator
(program_translator.py:775) rewrites the function's AST with ~20 transformers
(ifelse_transformer, loop_transformer, logical_transformer, ...) so
tensor-dependent Python control flow becomes `cond`/`while` *ops* in the
ProgramDesc.

TPU-native: the rewritten control flow lands on XLA's structured primitives —
`convert_ifelse` → `jax.lax.cond`, `convert_while_loop` → `jax.lax.while_loop`
— which is exactly what `@to_static` tracing needs: without the rewrite, a
`if tensor:` raises a concretization error under tracing; with it, the program
stays one compiled computation with native branches/loops.

Supported subset (the transformers that carry the reference's test weight):
  * `if`/`elif`/`else` on tensor or python predicates (SSA-style var merging)
  * `while` on tensor conditions (assigned names become loop carries)
  * `for i in range(...)` with tensor bounds (lowered to while)
  * `and`/`or`/`not` via convert_logical_* (short-circuit kept for python values)
Statements with early `return`/`break`/`continue` inside a transformed block
fall back to plain Python (they work for concrete predicates, like eager mode).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import threading
from typing import Callable

import jax

from ..core.tensor import Tensor

_state = threading.local()
_CONVERTED_CACHE = {}
_enabled = True


def enable_to_static(flag: bool):
    """ProgramTranslator.enable parity: globally toggle AST conversion."""
    global _enabled
    _enabled = bool(flag)


def _is_tensorish(x):
    return isinstance(x, Tensor) or isinstance(x, jax.core.Tracer) or \
        hasattr(x, "aval")


def _is_traced(x):
    """True only for values whose CONTENT is unknown (tracers). Concrete
    jax arrays have definite values — python control flow on them keeps
    dygraph semantics (and branch-local UnboundLocal errors) instead of
    forcing both branches through lax.cond."""
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


# --------------------------------------------------------------- runtime API
class _Undefined:
    """Placeholder for branch out-vars with no value before the `if`. Any USE
    raises the UnboundLocalError plain python would have raised — merely
    binding it (var assigned in the other branch, never read after) is legal,
    matching python's read-time semantics."""

    __slots__ = ("_name",)

    def __init__(self, name="<var>"):
        object.__setattr__(self, "_name", name)

    def __repr__(self):
        return f"<{object.__getattribute__(self, '_name')} undefined before if>"

    def _raise(self, *a, **k):
        name = object.__getattribute__(self, "_name")
        raise UnboundLocalError(
            f"local variable {name!r} referenced before assignment (it is only "
            f"assigned in one branch of a converted `if`)")

    __getattr__ = __call__ = __bool__ = __iter__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __eq__ = __ne__ = _raise
    __lt__ = __gt__ = __le__ = __ge__ = __neg__ = __matmul__ = _raise
    __pow__ = __rpow__ = __mod__ = __rmod__ = __divmod__ = _raise
    __floordiv__ = __rfloordiv__ = __abs__ = __pos__ = __invert__ = _raise
    __float__ = __int__ = __index__ = __complex__ = __hash__ = _raise
    __contains__ = __setitem__ = __delitem__ = __and__ = __or__ = _raise
    # (identity tests `z is None` are the one use python itself can't hook)


UNDEFINED = _Undefined()


def undefined(name):
    """init-capture hook for a not-yet-bound branch out-var."""
    return _Undefined(name)


def convert_ifelse(pred, true_fn, false_fn, init_args=()):
    """`if` lowering: lax.cond when the predicate is traced, python otherwise
    (reference convert_operators.py convert_ifelse).

    init_args carry the pre-branch values of every variable the branches
    assign: the branch functions take them as parameters so (a) a variable
    both read and written in a branch sees its outer value, and (b) under
    lax.cond each traced branch starts from the same initial state instead of
    observing the other branch's mutations."""
    if isinstance(pred, Tensor):
        pred = pred._data
    if _is_traced(pred):
        import jax.numpy as jnp

        p = jnp.reshape(pred.astype(bool) if pred.dtype != bool else pred, ())
        # closures (not operands): an UNDEFINED init must only fail if a
        # branch actually reads it
        try:
            return jax.lax.cond(p, lambda: true_fn(*init_args),
                                lambda: false_fn(*init_args))
        except TypeError as e:
            # only re-label when an undefined init is the plausible root
            # cause — a user TypeError mentioning "structure" must pass
            # through untouched
            if any(isinstance(a, _Undefined) for a in init_args) and (
                    "_Undefined" in str(e) or "structure" in str(e)):
                names = [object.__getattribute__(a, "_name")
                         for a in init_args if isinstance(a, _Undefined)]
                raise UnboundLocalError(
                    f"dy2static: variable(s) {names} are assigned in only "
                    f"one branch of a traced `if`; initialize them before "
                    f"the `if` so both lax.cond branches produce the same "
                    f"structure") from e
            raise
    if hasattr(pred, "item"):  # concrete array -> python bool
        pred = bool(pred)
    return true_fn(*init_args) if pred else false_fn(*init_args)


def convert_while_loop(cond_fn, body_fn, loop_vars, bound=None):
    """`while` lowering: lax.while_loop when the condition is traced
    (reference convert_while_loop). Loop carries are the assigned names.

    bound: optional (start, stop, step) from a range-for origin. When all
    three are CONCRETE, a traced condition lowers to a fixed-length
    lax.scan whose steps freeze the carry once the condition goes false —
    same semantics (the frozen state keeps the condition false), but
    reverse-differentiable, which lax.while_loop fundamentally is not
    (its transpose is undefined for dynamic trip counts). The scan always
    runs the full bound — the standard TPU trade: static shapes + grads
    for early-exit time."""
    first = cond_fn(*loop_vars)
    traced = _is_traced(first) or any(_is_traced(v) for v in loop_vars)
    if traced:
        bad = [object.__getattribute__(v, "_name") for v in loop_vars
               if isinstance(v, _Undefined)]
        if bad:
            # an UNDEFINED carry (name first assigned inside the body) has no
            # typed initial value for lax.while_loop. With a CONCRETE-backed
            # condition (vjp-over-concrete tracing) the python loop preserves
            # semantics — it just unrolls into the trace; only a genuinely
            # abstract condition is an error.
            try:
                bool(first._data if isinstance(first, Tensor) else first)
                traced = False
            except jax.errors.TracerBoolConversionError:
                raise UnboundLocalError(
                    f"dy2static: loop variable(s) {bad} are read in a traced "
                    f"`while` before being assigned; initialize them before "
                    f"the loop (lax.while_loop carries need a defined "
                    f"initial value)") from None
    if traced:
        import jax.numpy as jnp

        def cond(vs):
            c = cond_fn(*vs)
            c = c._data if isinstance(c, Tensor) else c
            return jnp.reshape(c.astype(bool) if c.dtype != bool else c, ())

        def body(vs):
            out = body_fn(*vs)
            return tuple(out) if isinstance(out, tuple) else (out,)

        max_trip = _concrete_trip_count(bound)
        if max_trip is not None:
            if max_trip == 0:
                return tuple(loop_vars)

            def scan_step(vs, _):
                c = cond(vs)
                new = body(vs)
                frozen = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(c, n, o), new, tuple(vs))
                return frozen, None

            final, _ = jax.lax.scan(scan_step, tuple(loop_vars), None,
                                    length=max_trip)
            return final

        return jax.lax.while_loop(cond, body, tuple(loop_vars))
    vs = tuple(loop_vars)
    while cond_fn(*vs):
        out = body_fn(*vs)
        vs = tuple(out) if isinstance(out, tuple) else (out,)
    return vs


def _concrete_trip_count(bound):
    """len(range(start, stop, step)) when every element is a concrete int
    (python int / 0-d non-traced integer array); None otherwise."""
    if bound is None:
        return None
    vals = []
    for b in bound:
        b = b._data if isinstance(b, Tensor) else b
        if _is_traced(b):
            return None
        try:
            vals.append(int(b))
        except (TypeError, ValueError):
            return None
    try:
        return len(range(*vals))
    except (TypeError, ValueError):
        return None


def convert_logical_and(x_fn: Callable, y_fn: Callable):
    x = x_fn()
    if isinstance(x, Tensor) or _is_tensorish(x):
        from ..ops import math as M

        return M.logical_and(x, y_fn())
    return x and y_fn()


def convert_logical_or(x_fn: Callable, y_fn: Callable):
    x = x_fn()
    if isinstance(x, Tensor) or _is_tensorish(x):
        from ..ops import math as M

        return M.logical_or(x, y_fn())
    return x or y_fn()


def convert_logical_not(x):
    if isinstance(x, Tensor) or _is_tensorish(x):
        from ..ops import math as M

        return M.logical_not(x)
    return not x


# ------------------------------------------------------------- AST analysis
class _NameCollector(ast.NodeVisitor):
    """Names assigned at any depth of a block, excluding nested functions."""

    def __init__(self):
        self.stored = []

    def visit_FunctionDef(self, node):  # don't descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store) and node.id not in self.stored:
            self.stored.append(node.id)

    def visit_AugAssign(self, node):
        if isinstance(node.target, ast.Name) and node.target.id not in self.stored:
            self.stored.append(node.target.id)
        self.generic_visit(node)

    # match PATTERNS bind names as plain strings, not Name(Store) nodes:
    # `case {"m": m}` / `case [x, *rest]` / `case P() as y` assign m/rest/y.
    # Missing them would drop pattern-bound names from loop carries, so a
    # lowered return/break under `match` would NameError post-loop.
    def _match_binding(self, node):
        name = getattr(node, "name", None) or getattr(node, "rest", None)
        if name and name not in self.stored:
            self.stored.append(name)
        self.generic_visit(node)

    visit_MatchAs = _match_binding
    visit_MatchStar = _match_binding
    visit_MatchMapping = _match_binding


def _assigned_names(stmts):
    c = _NameCollector()
    for s in stmts:
        c.visit(s)
    # __dy2st_* temps belong to already-transformed inner blocks, not the user
    return [n for n in c.stored if not n.startswith("__dy2st_")]


class _HasEscape(ast.NodeVisitor):
    """Detects return/break/continue (at this block's depth, not nested fns)."""

    def __init__(self):
        self.found = False

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        self.found = True

    def visit_Continue(self, node):
        self.found = True


def _has_escape(stmts) -> bool:
    v = _HasEscape()
    for s in stmts:
        v.visit(s)
    return v.found


class _EscapeScan(ast.NodeVisitor):
    """break/continue belonging to THIS loop level (nested loops swallow their
    own) + return at any depth (excluding nested functions)."""

    def __init__(self):
        self.brk = self.cont = self.ret = False

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _nested_loop(self, node):
        inner_body = _scan_level(node.body)
        self.ret = self.ret or inner_body.ret
        # the inner loop's ELSE clause is OUTSIDE that loop for escape
        # purposes: a break/continue there targets THIS level (python
        # scoping), so it must not be swallowed with the inner body's
        inner_else = _scan_level(node.orelse)
        self.ret = self.ret or inner_else.ret
        self.brk = self.brk or inner_else.brk
        self.cont = self.cont or inner_else.cont

    visit_While = visit_For = _nested_loop

    # With/Try bodies count as this level: _guard rewrites through them

    def visit_Return(self, node):
        self.ret = True

    def visit_Break(self, node):
        self.brk = True

    def visit_Continue(self, node):
        self.cont = True


def _scan_level(stmts) -> _EscapeScan:
    v = _EscapeScan()
    for s in stmts:
        v.visit(s)
    return v


def _contains_return(stmts) -> bool:
    return _scan_level(stmts if isinstance(stmts, list) else [stmts]).ret


def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_load("_jst"), attr=fn_name, ctx=ast.Load()),
        args=args, keywords=[])


def _capture_inits(names, prefix):
    """Pre-statements snapshotting each name into `<prefix>_<i>`; a name not
    yet bound becomes an _jst.undefined placeholder (read-before-assign then
    fails with a clear message only if actually read). Shared by the if- and
    while-emitters so their capture contracts cannot diverge.
    Returns (load_exprs, init_stmts)."""
    inits, init_stmts = [], []
    for i, v in enumerate(names):
        iname = f"{prefix}_{i}"
        inits.append(_load(iname))
        init_stmts.append(ast.Try(
            body=[ast.Assign(targets=[_store(iname)], value=_load(v))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(elts=[_load("NameError"),
                                     _load("UnboundLocalError")],
                               ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[_store(iname)],
                    value=_jst_call("undefined", [ast.Constant(value=v)]))])],
            orelse=[], finalbody=[]))
    return inits, init_stmts


# ---------------------------------------------------------- the transformer
def _range_for_to_while(node, uid: str):
    """`for i in range(...)` -> (init_stmts, ast.While) or None if not
    range-style. Shared by _Dy2Static.visit_For and the escape lowering."""
    if (not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or not isinstance(node.target, ast.Name)
            or not 1 <= len(node.iter.args) <= 3):
        return None
    i = node.target.id
    start_n, stop_n, step_n = (f"__dy2st_start_{uid}", f"__dy2st_stop_{uid}",
                               f"__dy2st_step_{uid}")
    a = node.iter.args
    start = a[0] if len(a) >= 2 else ast.Constant(value=0)
    stop = a[1] if len(a) >= 2 else a[0]
    step = a[2] if len(a) == 3 else ast.Constant(value=1)
    init = [
        ast.Assign(targets=[_store(start_n)], value=start),
        ast.Assign(targets=[_store(stop_n)], value=stop),
        ast.Assign(targets=[_store(step_n)], value=step),
        ast.Assign(targets=[_store(i)], value=_load(start_n)),
    ]
    # i*sign < stop*sign: python-level sign check for constant steps; tensor
    # steps assume positive
    if isinstance(step, ast.Constant) and isinstance(step.value, int) and \
            step.value < 0:
        test = ast.Compare(left=_load(i), ops=[ast.Gt()],
                           comparators=[_load(stop_n)])
    else:
        test = ast.Compare(left=_load(i), ops=[ast.Lt()],
                           comparators=[_load(stop_n)])
    incr = ast.AugAssign(target=_store(i), op=ast.Add(), value=_load(step_n))
    # incr returned separately: escape lowering must keep it OUTSIDE the
    # continue-guard (python's `continue` jumps TO the increment)
    wh = ast.While(test=test, body=list(node.body), orelse=[])
    # range-for origin: the trip count is bounded by (start, stop, step).
    # The names are threaded to convert_while_loop so a TRACED condition
    # (break flag under a tensor `if`) can lower to a fixed-length scan with
    # frozen-state selects — reverse-differentiable, unlike lax.while_loop.
    wh._dy2st_bound = (start_n, stop_n, step_n)
    return init, wh, incr


def _warn_fallback(what: str, why: str):
    import warnings

    warnings.warn(
        f"dy2static: {what} falls back to plain Python ({why}); under tracing "
        f"this leaves the one-XLA-computation world", stacklevel=2)


def _returns_always(stmts) -> bool:
    """Every path through `stmts` ends in a return (conservative)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Return):
        return True
    if isinstance(last, ast.If):
        return (_returns_always(last.body) and last.orelse
                and _returns_always(last.orelse))
    return False


class _ReturnCPS:
    """Early-`return` lowering (reference return_transformer.py): rewrite the
    function body in continuation-passing style so every path assigns the
    single return slot exactly once and the function ends with one `return`.
    `if` statements containing returns get the continuation inlined into both
    branches — so under tracing both lax.cond branches produce the return
    value and no undefined-variable pytree mismatch arises.

    Returns inside loops are NOT lowered (the return value would need a
    shape-known loop carry before tracing); those functions keep the Python
    fallback with a warning.
    """

    RV = "__esc_rv"

    @classmethod
    def applicable(cls, fdef) -> bool:
        body = fdef.body
        if not _contains_return(body):
            return False
        if len(body) and isinstance(body[-1], ast.Return) \
                and not _contains_return(body[:-1]):
            return False  # single tail return: nothing to lower
        if not _returns_always(body):
            # a fall-through path returns implicit None, which cannot mix with
            # tensor returns under lax.cond — keep the python fallback
            _warn_fallback(f"function {fdef.name!r}",
                           "may fall through without an explicit return")
            return False
        # walk WITHOUT descending into nested function definitions
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if isinstance(node, (ast.While, ast.For)) \
                    and _contains_return(node.body + node.orelse):
                _warn_fallback(f"function {fdef.name!r}",
                               "return inside a loop body")
                return False
            if isinstance(node, (ast.Try, ast.With)) \
                    and _contains_return(getattr(node, "body", [])):
                _warn_fallback(f"function {fdef.name!r}",
                               "return inside try/with")
                return False
            stack.extend(ast.iter_child_nodes(node))
        return True

    @classmethod
    def lower(cls, fdef):
        final = [ast.Assign(targets=[_store(cls.RV)],
                            value=ast.Constant(value=None))]
        fdef.body = cls._cps(fdef.body, final) + [
            ast.Return(value=_load(cls.RV))]

    @classmethod
    def _cps(cls, stmts, continuation):
        if not stmts:
            return list(continuation)
        s, rest = stmts[0], stmts[1:]
        if isinstance(s, ast.Return):
            val = s.value if s.value is not None else ast.Constant(value=None)
            return [ast.Assign(targets=[_store(cls.RV)], value=val)]
        if isinstance(s, ast.If) and _contains_return([s]):
            k2 = cls._cps(rest, continuation)
            return [ast.If(test=s.test, body=cls._cps(s.body, k2),
                           orelse=cls._cps(s.orelse, k2))]
        return [s] + cls._cps(rest, continuation)


def _returns_at_level(stmts) -> bool:
    """Return statements _ReturnInLoopLowering._rewrite can actually reach:
    descends If/With and finalbody-free Try — NOT nested loops (they lower
    their own), function definitions, or anything else (match, try/finally:
    a finally that assigns would corrupt the post-loop re-evaluation).
    MUST stay symmetric with _rewrite's traversal, or lowering triggers on
    a return it then cannot rewrite."""
    for s in stmts:
        if isinstance(s, ast.Return):
            return True
        if isinstance(s, ast.If):
            if _returns_at_level(s.body) or _returns_at_level(s.orelse):
                return True
        elif isinstance(s, ast.With):
            if _returns_at_level(s.body):
                return True
        elif isinstance(s, ast.Try) and not s.finalbody:
            if _returns_at_level(s.body) or _returns_at_level(s.orelse) or \
                    any(_returns_at_level(h.body) for h in s.handlers):
                return True
        elif isinstance(s, ast.Match):
            # case bodies are mutually exclusive like If branches; patterns
            # only BIND names (before the body), so body rewriting is safe
            if any(_returns_at_level(c.body) for c in s.cases):
                return True
    return False


class _ReturnInLoopLowering(ast.NodeTransformer):
    """return-inside-loop lowering (VERDICT r2 #8; the reference's
    return_transformer.py RETURN_NO_VALUE machinery): `return EXPR` in a
    loop body becomes `done = True; site = k; break`, and the loop is
    followed by `if done: return <EXPR_k chain>` with each EXPR re-evaluated
    on the final carry state.

    Correctness: the lowered break exits the (converted) loop immediately
    and flag-guards every later write, so at loop exit the assigned names —
    which are exactly the loop carries — hold the values they had at the
    return site; re-evaluating EXPR after the loop reads the same values.
    Runs BEFORE _BreakContinueLowering (which lowers the emitted break) and
    _ReturnCPS (which lowers the post-loop conditional returns).
    """

    def __init__(self):
        self._n = 0

    def _visit_loop(self, node):
        self.generic_visit(node)  # innermost loops first
        if not _returns_at_level(node.body):
            return node
        self._n += 1
        done, rid = f"__esc_rdone_{self._n}", f"__esc_rid_{self._n}"
        orelse_guard = None
        if node.orelse and _scan_level(node.body).brk:
            # return + loop-else + USER break (VERDICT r4 missing #2): a
            # user break must skip the else, but at this pass the break is
            # still a raw `break` — so tag each one with its own flag
            # (`ubrk = True; break`) BEFORE lowering returns, and guard the
            # else on `not done and not ubrk`. _BreakContinueLowering later
            # lowers both the tagged user breaks and our emitted ones into
            # its carry flags, keeping the loop one lax.while_loop.
            ubrk = f"__esc_ubrk_{self._n}"
            node.body = self._tag_user_breaks(node.body, ubrk)
            orelse_guard = ubrk
        sites = []
        node.body = self._rewrite(node.body, done, rid, sites)
        stmt = ast.Return(value=sites[-1][1])
        for k, expr in reversed(sites[:-1]):
            stmt = ast.If(
                test=ast.Compare(left=_load(rid), ops=[ast.Eq()],
                                 comparators=[ast.Constant(value=k)]),
                body=[ast.Return(value=expr)], orelse=[stmt])
        # loop-else moves into the post-If's orelse: python runs the else
        # only on normal completion, and a lowered return (done=True) exits
        # via break — not normal completion — so `else` and `return` are
        # exactly the two arms of `if done` (VERDICT r3 missing #2); with
        # user breaks in play the else additionally requires `not ubrk`
        orelse = node.orelse
        if orelse_guard is not None:
            orelse = [ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_load(orelse_guard)),
                body=list(node.orelse), orelse=[])]
        post = ast.If(test=_load(done), body=[stmt], orelse=orelse)
        node.orelse = []
        init = [ast.Assign(targets=[_store(done)],
                           value=ast.Constant(value=False)),
                ast.Assign(targets=[_store(rid)],
                           value=ast.Constant(value=0))]
        if orelse_guard is not None:
            init.append(ast.Assign(targets=[_store(orelse_guard)],
                                   value=ast.Constant(value=False)))
        return init + [node, post]

    def _tag_user_breaks(self, stmts, ubrk):
        """Prefix every user `break` belonging to THIS loop level with
        `ubrk = True`. Same this-level traversal as _EscapeScan: descends
        If/With/Try/Match; a nested loop swallows its own body breaks but
        its orelse belongs to this level (python scoping)."""
        out = []
        for s in stmts:
            if isinstance(s, ast.Break):
                out += [ast.Assign(targets=[_store(ubrk)],
                                   value=ast.Constant(value=True)), s]
            else:
                if isinstance(s, ast.If):
                    s.body = self._tag_user_breaks(s.body, ubrk)
                    s.orelse = self._tag_user_breaks(s.orelse, ubrk)
                elif isinstance(s, ast.With):
                    s.body = self._tag_user_breaks(s.body, ubrk)
                elif isinstance(s, ast.Try):
                    s.body = self._tag_user_breaks(s.body, ubrk)
                    for h in s.handlers:
                        h.body = self._tag_user_breaks(h.body, ubrk)
                    s.orelse = self._tag_user_breaks(s.orelse, ubrk)
                    s.finalbody = self._tag_user_breaks(s.finalbody, ubrk)
                elif isinstance(s, ast.Match):
                    for c in s.cases:
                        c.body = self._tag_user_breaks(c.body, ubrk)
                elif isinstance(s, (ast.While, ast.For)):
                    s.orelse = self._tag_user_breaks(s.orelse, ubrk)
                out.append(s)
        return out

    visit_While = _visit_loop
    visit_For = _visit_loop

    def _rewrite(self, stmts, done, rid, sites):
        out = []
        for s in stmts:
            if isinstance(s, ast.Return):
                k = len(sites) + 1
                sites.append((k, s.value if s.value is not None
                              else ast.Constant(value=None)))
                out += [ast.Assign(targets=[_store(done)],
                                   value=ast.Constant(value=True)),
                        ast.Assign(targets=[_store(rid)],
                                   value=ast.Constant(value=k)),
                        ast.Break()]
            elif isinstance(s, ast.If):
                s.body = self._rewrite(s.body, done, rid, sites)
                s.orelse = self._rewrite(s.orelse, done, rid, sites)
                out.append(s)
            elif isinstance(s, ast.With):
                s.body = self._rewrite(s.body, done, rid, sites)
                out.append(s)
            elif isinstance(s, ast.Try) and not s.finalbody:
                # try/finally is excluded (symmetric with _returns_at_level):
                # a finally that assigns names would run between the lowered
                # break and the post-loop re-evaluation, corrupting the
                # return value python would have computed first
                s.body = self._rewrite(s.body, done, rid, sites)
                for h in s.handlers:
                    h.body = self._rewrite(h.body, done, rid, sites)
                s.orelse = self._rewrite(s.orelse, done, rid, sites)
                out.append(s)
            elif isinstance(s, ast.Match):
                for c in s.cases:
                    c.body = self._rewrite(c.body, done, rid, sites)
                out.append(s)
            else:
                out.append(s)
        return out


def _nested_else_break_conflict(stmts) -> bool:
    """True when a nested loop AT THIS LEVEL both (a) has break/continue in
    its orelse (targeting the enclosing loop) and (b) still carries an
    unlowered break in its own body (innermost-first lowering left it — e.g.
    a non-range for). Then the nested else is CONDITIONAL on that body break,
    so _guard's hoist-the-else rewrite would run it unconditionally — the
    enclosing loop must fall back instead. Traversal mirrors _scan_level's
    this-level rule (descends If/With/Try/Match, not nested-loop bodies)."""
    for s in stmts:
        if isinstance(s, (ast.While, ast.For)):
            e = _scan_level(s.orelse)
            if (e.brk or e.cont) and _scan_level(s.body).brk:
                return True
            # the orelse is this level's scope: conflicts nest there too
            if _nested_else_break_conflict(s.orelse):
                return True
        elif isinstance(s, ast.If):
            if _nested_else_break_conflict(s.body) or \
                    _nested_else_break_conflict(s.orelse):
                return True
        elif isinstance(s, ast.With):
            if _nested_else_break_conflict(s.body):
                return True
        elif isinstance(s, ast.Try):
            blocks = [s.body, s.orelse, s.finalbody] + \
                [h.body for h in s.handlers]
            if any(_nested_else_break_conflict(b) for b in blocks):
                return True
        elif isinstance(s, ast.Match):
            if any(_nested_else_break_conflict(c.body) for c in s.cases):
                return True
    return False


class _BreakContinueLowering(ast.NodeTransformer):
    """break/continue lowering (reference break_continue_transformer.py):
    rewrite them into boolean flag assignments, guard the statements after a
    potential escape with `if not flag:`, and fold `not break_flag` into the
    loop condition — after which the loop body is escape-free and the While
    transformer lowers the whole loop to lax.while_loop (flags are plain bool
    loop carries).
    """

    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return f"esc{self._n}"

    def visit_While(self, node):
        self.generic_visit(node)  # innermost loops first
        scan = _scan_level(node.body)
        if not (scan.brk or scan.cont):
            return node
        if scan.ret:
            # only reachable when _ReturnInLoopLowering could not rewrite
            # (return+else+break, try/finally, non-range for); keep the loud
            # fallback
            _warn_fallback("while loop", "return inside the loop body")
            return node
        if _nested_else_break_conflict(node.body):
            _warn_fallback("while loop",
                           "break in a nested loop's else, where the nested "
                           "loop keeps an unlowered break")
            return node
        return self._lower(node, orelse=node.orelse)

    def visit_For(self, node):
        self.generic_visit(node)
        scan = _scan_level(node.body)
        if not (scan.brk or scan.cont):
            return node
        if scan.ret:
            _warn_fallback("for loop", "return inside the loop body")
            return node
        if _nested_else_break_conflict(node.body):
            _warn_fallback("for loop",
                           "break in a nested loop's else, where the nested "
                           "loop keeps an unlowered break")
            return node
        conv = _range_for_to_while(node, f"bc_{self._uid()}")
        if conv is None:
            _warn_fallback("for loop", "break/continue in a non-range for")
            return node
        init, loop, incr = conv
        return init + self._lower(loop, trailing=[incr], orelse=node.orelse)

    def _lower(self, node, trailing=(), orelse=()):
        uid = self._uid()
        brk, cont = f"__esc_brk_{uid}", f"__esc_cont_{uid}"
        body = [ast.Assign(targets=[_store(cont)],
                           value=ast.Constant(value=False))]
        body += self._guard(node.body, brk, cont)
        # trailing (a for-range increment) runs after `continue` (python's
        # continue jumps to the increment) but NOT after `break` (which exits
        # immediately, leaving the loop variable at its python value)
        if trailing:
            body.append(ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_load(brk)),
                body=list(trailing), orelse=[]))
        test = ast.BoolOp(op=ast.And(), values=[
            ast.UnaryOp(op=ast.Not(), operand=_load(brk)), node.test])
        init = [ast.Assign(targets=[_store(n)], value=ast.Constant(value=False))
                for n in (brk, cont)]
        wh = ast.While(test=test, body=body, orelse=[])
        if getattr(node, "_dy2st_bound", None):
            wh._dy2st_bound = node._dy2st_bound  # keep the scan-able bound
        out = init + [wh]
        if orelse:
            # loop-else via the broke-flag (VERDICT r3 missing #2): python
            # runs the else only when the loop completes WITHOUT break. The
            # lowered loop always completes "normally" (break became a flag
            # folded into the condition), so the else must NOT ride on the
            # While — it runs under `if not brk`. Continue-only loops keep
            # brk False, so their else always runs, as in python.
            out.append(ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_load(brk)),
                body=list(orelse), orelse=[]))
        return out

    def _guard(self, stmts, brk, cont):
        out = []
        for idx, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(ast.Assign(targets=[_store(brk)],
                                      value=ast.Constant(value=True)))
                escaped = True
            elif isinstance(s, ast.Continue):
                out.append(ast.Assign(targets=[_store(cont)],
                                      value=ast.Constant(value=True)))
                escaped = True
            elif isinstance(s, ast.If):
                scan = _scan_level(s.body + s.orelse)
                if scan.brk or scan.cont:
                    out.append(ast.If(test=s.test,
                                      body=self._guard(s.body, brk, cont) or
                                      [ast.Pass()],
                                      orelse=self._guard(s.orelse, brk, cont)))
                    escaped = True
                else:
                    out.append(s)
                    escaped = False
            elif isinstance(s, ast.With):
                scan = _scan_level(s.body)
                if scan.brk or scan.cont:
                    # flag-set + guard inside the with; __exit__ still runs
                    # at block end — python's break also runs __exit__, and
                    # every skipped statement is guarded, so ordering is the
                    # only (unobservable) difference
                    s.body = self._guard(s.body, brk, cont) or [ast.Pass()]
                    out.append(s)
                    escaped = True
                else:
                    out.append(s)
                    escaped = False
            elif isinstance(s, ast.Try):
                blocks = [s.body, s.orelse, s.finalbody] + \
                    [h.body for h in s.handlers]
                if any(_scan_level(b).brk or _scan_level(b).cont
                       for b in blocks):
                    s.body = self._guard(s.body, brk, cont) or [ast.Pass()]
                    for h in s.handlers:
                        h.body = self._guard(h.body, brk, cont) or [ast.Pass()]
                    if s.orelse:
                        # python's break in the try body SKIPS the else
                        # clause; after flag-lowering the body "completes
                        # normally", so the else must be alive-guarded
                        alive = ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                            op=ast.Or(), values=[_load(brk), _load(cont)]))
                        s.orelse = [ast.If(
                            test=alive,
                            body=self._guard(s.orelse, brk, cont),
                            orelse=[])]
                    s.finalbody = self._guard(s.finalbody, brk, cont)
                    out.append(s)
                    escaped = True
                else:
                    out.append(s)
                    escaped = False
            elif isinstance(s, ast.Match):
                if any(_scan_level(c.body).brk or _scan_level(c.body).cont
                       for c in s.cases):
                    for c in s.cases:
                        c.body = self._guard(c.body, brk, cont) or [ast.Pass()]
                    out.append(s)
                    escaped = True
                else:
                    out.append(s)
                    escaped = False
            elif isinstance(s, (ast.While, ast.For)):
                # a nested loop swallows its OWN body escapes, but its else
                # clause is this level's scope: a break/continue there
                # targets the loop being lowered (caught by _EscapeScan's
                # matching rule). A nested loop still owning an orelse here
                # had no body break (innermost-first lowering would have
                # stripped it) and no return (the outer visit falls back on
                # scan.ret before _guard runs) — so its else ALWAYS runs:
                # hoist it after the loop, where this level's flags guard it
                # and the emitter sees an orelse-free inner loop.
                scan_e = _scan_level(s.orelse)
                if scan_e.brk or scan_e.cont:
                    hoisted = s.orelse
                    s.orelse = []
                    out.append(s)
                    out += self._guard(hoisted, brk, cont)
                    escaped = True
                else:
                    out.append(s)
                    escaped = False
            else:
                out.append(s)
                escaped = False
            if escaped and idx + 1 < len(stmts):
                rest = self._guard(stmts[idx + 1:], brk, cont)
                alive = ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                    op=ast.Or(), values=[_load(brk), _load(cont)]))
                out.append(ast.If(test=alive, body=rest, orelse=[]))
                break
        return out


class _Dy2Static(ast.NodeTransformer):
    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # --- bool ops ---
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[0]
        for rhs in node.values[1:]:
            out = _jst_call(fn, [
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              kwonlyargs=[], kw_defaults=[],
                                              defaults=[]), body=out),
                ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                              kwonlyargs=[], kw_defaults=[],
                                              defaults=[]), body=rhs),
            ])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # --- if/else ---
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            # escapes the lowering passes could not remove (e.g. inside
            # try/with): loud fallback, not silence
            _warn_fallback("if statement", "unlowered return/break/continue")
            return node  # python fallback (concrete predicates only)
        out_vars = _assigned_names(node.body + node.orelse)
        if not out_vars:
            return node  # side-effect-only branches: leave to python
        uid = self._uid()
        t_name, f_name = f"__dy2st_true_{uid}", f"__dy2st_false_{uid}"
        ret = ast.Return(value=ast.Tuple(
            elts=[_load(v) for v in out_vars], ctx=ast.Load()))
        # branches take the out-vars as PARAMETERS carrying their pre-branch
        # values: a name read-then-written in a branch resolves to the param
        # (python would otherwise make it an unbound local of the nested fn),
        # and lax.cond traces both branches from identical initial state
        branch_args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in out_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        t_def = ast.FunctionDef(name=t_name, args=branch_args,
                                body=list(node.body) + [ret], decorator_list=[],
                                type_params=[])
        f_body = list(node.orelse) + [ret]
        f_def = ast.FunctionDef(name=f_name, args=branch_args, body=f_body,
                                decorator_list=[], type_params=[])
        # capture initial values; vars not yet bound become UNDEFINED
        inits, init_stmts = _capture_inits(out_vars, f"__dy2st_init_{uid}")
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_store(v) for v in out_vars],
                               ctx=ast.Store())],
            value=_jst_call("convert_ifelse",
                            [node.test, _load(t_name), _load(f_name),
                             ast.Tuple(elts=inits, ctx=ast.Load())]))
        return init_stmts + [t_def, f_def, assign]

    # --- while ---
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            _warn_fallback("while loop",
                           "unlowered escape statement or while/else")
            return node
        loop_vars = _assigned_names(node.body)
        if not loop_vars:
            return node
        uid = self._uid()
        c_name, b_name = f"__dy2st_cond_{uid}", f"__dy2st_body_{uid}"
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=v) for v in loop_vars],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        c_def = ast.FunctionDef(
            name=c_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[_load(v) for v in loop_vars], ctx=ast.Load()))
        b_def = ast.FunctionDef(name=b_name, args=args,
                                body=list(node.body) + [ret], decorator_list=[],
                                type_params=[])
        bound = getattr(node, "_dy2st_bound", None)
        bound_ast = (ast.Tuple(elts=[_load(n) for n in bound],
                               ctx=ast.Load())
                     if bound else ast.Constant(value=None))
        # capture initial carry values; names first assigned INSIDE the loop
        # body become UNDEFINED placeholders (same contract as the if-branch
        # inits): convert_while_loop errors clearly if a traced loop reads
        # them before assignment, and the python path just writes over them
        inits, init_stmts = _capture_inits(loop_vars, f"__dy2st_lv_{uid}")
        assign = ast.Assign(
            targets=[ast.Tuple(elts=[_store(v) for v in loop_vars],
                               ctx=ast.Store())],
            value=_jst_call("convert_while_loop",
                            [_load(c_name), _load(b_name),
                             ast.Tuple(elts=inits, ctx=ast.Load()),
                             bound_ast]))
        return init_stmts + [c_def, b_def, assign]

    # --- for i in range(...) ---
    def visit_For(self, node):
        self.generic_visit(node)
        if _has_escape(node.body):
            _warn_fallback("for loop", "unlowered escape statement")
            return node
        if node.orelse:
            return node
        conv = _range_for_to_while(node, self._uid())
        if conv is None:
            return node
        init, loop, incr = conv
        loop.body = loop.body + [incr]
        out = init + [self.visit_While(loop)]
        flat = []
        for o in out:
            (flat.extend if isinstance(o, list) else flat.append)(o)
        return flat


# ------------------------------------------------------------- entry points
def convert_to_static(fn):
    """Rewrite `fn`'s AST (cached). Returns the original on any failure —
    code without tensor-dependent control flow behaves identically either way."""
    if not _enabled:
        return fn
    key = getattr(fn, "__func__", fn)
    if key in _CONVERTED_CACHE:
        return _CONVERTED_CACHE[key]
    converted = _convert(fn)
    _CONVERTED_CACHE[key] = converted
    return converted


def _convert(fn):
    raw = getattr(fn, "__func__", fn)
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []  # don't re-apply @to_static etc.
    # escape lowering first (reference break_continue/return transformers),
    # so the If/While transformers below see escape-free blocks. Order:
    # returns-in-loops become flagged breaks + post-loop conditional
    # returns, THEN CPS lowers all remaining returns, THEN break/continue
    # (incl. the ones just emitted) lower to loop-carried flags.
    tree = _ReturnInLoopLowering().visit(tree)
    fdef = tree.body[0]
    if _ReturnCPS.applicable(fdef):
        _ReturnCPS.lower(fdef)
    tree = _BreakContinueLowering().visit(tree)
    new_tree = _Dy2Static().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(raw.__globals__)
    from . import dy2static as _jst_mod

    glb["_jst"] = _jst_mod
    # freevars: bind current closure cell values as globals of the new function
    if raw.__closure__:
        for name, cell in zip(raw.__code__.co_freevars, raw.__closure__):
            try:
                glb.setdefault(name, cell.cell_contents)
            except ValueError:
                return fn  # unfilled cell (recursive def): fall back
    try:
        code = compile(new_tree, filename=f"<dy2static {raw.__name__}>",
                       mode="exec")
        exec(code, glb)
        new_fn = glb[fdef.name]
    except Exception:
        return fn
    functools.update_wrapper(new_fn, raw, updated=[])
    new_fn.__dy2static_source__ = ast.unparse(new_tree)
    if hasattr(fn, "__self__"):  # rebind methods
        return new_fn.__get__(fn.__self__, type(fn.__self__))
    return new_fn


def get_code(fn) -> str:
    """Transformed source (reference StaticFunction.code)."""
    converted = convert_to_static(fn)
    return getattr(converted, "__dy2static_source__",
                   inspect.getsource(getattr(fn, "__func__", fn)))

"""paddle.jit equivalent: dygraph -> static (traced XLA program).

The reference converts dygraph to static graphs with a 20-transformer AST transpiler
(python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:775) and runs the converted
ProgramDesc via run_program_op. TPU-natively the conversion is *tracing*: `functional_call` swaps
a Layer's parameters for traced arrays and replays its Python forward under jax tracing, so the
whole program (and, through jax.vjp, its backward) becomes ONE XLA computation. `to_static`
packages that as a single dispatch-op so the eager autograd tape differentiates through it —
static mode *is* the fused fast path, matching the reference's intent (InterpreterCore fusing an
instruction list) with XLA doing the scheduling.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.dispatch import apply
from ..core.tensor import Tensor

_trace_state = threading.local()


def in_jit_trace() -> bool:
    return getattr(_trace_state, "tracing", False)


@contextlib.contextmanager
def _tracing():
    prev = getattr(_trace_state, "tracing", False)
    _trace_state.tracing = True
    try:
        yield
    finally:
        _trace_state.tracing = prev


@contextlib.contextmanager
def _swapped_state(layer, state: Dict[str, Any]):
    """Temporarily replace the layer's parameter/buffer storage with the given arrays."""
    named = dict(layer.state_dict(include_non_persistable_buffer=True))
    originals = {}
    try:
        for name, arr in state.items():
            t = named[name]
            originals[name] = t._data
            t._data = arr._data if isinstance(arr, Tensor) else arr
        yield
    finally:
        for name, old in originals.items():
            named[name]._data = old


def functional_call(layer, state: Dict[str, Any], *args, **kwargs):
    """Run `layer` with its params/buffers taken from `state` (name -> array/Tensor).

    The bridge between eager Layers and traced/pjit execution (torch.func.functional_call
    analogue). Autograd recording is disabled inside — differentiate with jax.grad around it.
    """
    with _swapped_state(layer, state), _tracing(), no_grad():
        return layer(*args, **kwargs)


def _unwrap(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (list, tuple)):
        return type(out)(_unwrap(o) for o in out)
    return out


def _wrap_inputs(args):
    return [a if isinstance(a, Tensor) else Tensor(jnp.asarray(a)) for a in args]


class StaticFunction:
    """Callable produced by @to_static: runs the layer as one traced XLA computation,
    differentiable through the eager tape (the computation appears as a single grad node)."""

    def __init__(self, layer=None, function=None, input_spec=None, build_strategy=None):
        self._layer = layer
        self._function = function
        self._jitted = None
        self._param_names = []

    def _build_kernel(self, n_inputs, kwargs):
        layer = self._layer
        function = self._function
        param_names = self._param_names

        def kernel(*arrays):
            param_arrays = arrays[:len(param_names)]
            input_arrays = arrays[len(param_names):]
            inputs = [Tensor(a, stop_gradient=True) for a in input_arrays]
            if layer is not None:
                state = dict(zip(param_names, param_arrays))
                with _swapped_state(layer, state), _tracing(), no_grad():
                    out = (function or layer.forward)(*inputs, **kwargs)
            else:
                with _tracing(), no_grad():
                    out = function(*inputs, **kwargs)
            return _unwrap(out)

        return kernel

    def __call__(self, *args, **kwargs):
        inputs = _wrap_inputs(args)
        if self._layer is not None:
            state = self._layer.state_dict(include_non_persistable_buffer=True)
            self._param_names = list(state.keys())
            tensor_args = [state[n] for n in self._param_names] + inputs
        else:
            tensor_args = inputs
        kernel = self._build_kernel(len(inputs), kwargs)
        return apply("to_static_program", kernel, tensor_args)


def to_static(layer_or_function=None, input_spec=None, build_strategy=None, **kwargs):
    from ..nn.layer import Layer

    def decorate(obj):
        if isinstance(obj, Layer):
            orig_forward = obj.forward
            obj.forward = StaticFunction(layer=obj, function=orig_forward)
            return obj
        bound_self = getattr(obj, "__self__", None)
        if isinstance(bound_self, Layer):
            # bound method of a Layer: its parameters must flow through the traced
            # program as inputs, or gradients silently stop at the jit boundary
            return StaticFunction(layer=bound_self, function=obj)
        import functools

        # plain function
        fn = StaticFunction(function=obj)
        functools.update_wrapper(fn, obj, updated=[])
        return fn

    if layer_or_function is None:
        return decorate
    return decorate(layer_or_function)


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity: persist params + a marker (program serialization lands with
    the static Program IR, static/)."""
    from ..framework import io as fio

    fio.save(layer.state_dict(), path + ".pdparams")


def load(path, **configs):
    raise NotImplementedError("jit.load: lands with static Program IR")


class TranslatedLayer:
    pass


def not_to_static(fn):
    return fn


def ignore_module(modules):
    pass

"""paddle.jit equivalent: dygraph -> static (traced XLA program).

The reference converts dygraph to static graphs with a 20-transformer AST transpiler
(python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:775) and runs the converted
ProgramDesc via run_program_op. TPU-natively the conversion is *tracing*: `functional_call` swaps
a Layer's parameters for traced arrays and replays its Python forward under jax tracing, so the
whole program (and, through jax.vjp, its backward) becomes ONE XLA computation. `to_static`
packages that as a single dispatch-op so the eager autograd tape differentiates through it —
static mode *is* the fused fast path, matching the reference's intent (InterpreterCore fusing an
instruction list) with XLA doing the scheduling.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.dispatch import apply
from ..core.tensor import Tensor

_trace_state = threading.local()


def in_jit_trace() -> bool:
    return getattr(_trace_state, "tracing", False)


@contextlib.contextmanager
def _tracing():
    prev = getattr(_trace_state, "tracing", False)
    _trace_state.tracing = True
    try:
        yield
    finally:
        _trace_state.tracing = prev


@contextlib.contextmanager
def _swapped_state(layer, state: Dict[str, Any]):
    """Temporarily replace the layer's parameter/buffer storage with the given arrays."""
    named = dict(layer.state_dict(include_non_persistable_buffer=True))
    originals = {}
    try:
        for name, arr in state.items():
            t = named[name]
            originals[name] = t._data
            t._data = arr._data if isinstance(arr, Tensor) else arr
        yield
    finally:
        for name, old in originals.items():
            named[name]._data = old


def functional_call(layer, state: Dict[str, Any], *args, **kwargs):
    """Run `layer` with its params/buffers taken from `state` (name -> array/Tensor).

    The bridge between eager Layers and traced/pjit execution (torch.func.functional_call
    analogue). Autograd recording is disabled inside — differentiate with jax.grad around it.
    """
    with _swapped_state(layer, state), _tracing(), no_grad():
        return layer(*args, **kwargs)


def functional_call_with_state(layer, state: Dict[str, Any], *args, **kwargs):
    """functional_call that also returns the post-forward state arrays, capturing
    in-place buffer mutations the forward performed (BatchNorm running stats).
    Returns (out, new_state: name -> array)."""
    named = dict(layer.state_dict(include_non_persistable_buffer=True))
    with _swapped_state(layer, state), _tracing(), no_grad():
        out = layer(*args, **kwargs)
        # read BEFORE _swapped_state restores the originals: the layer's tensors
        # currently hold the traced (possibly updated) arrays
        new_state = {name: t._data for name, t in named.items()}
    return out, new_state


def _unwrap(out):
    if isinstance(out, Tensor):
        return out._data
    if isinstance(out, (list, tuple)):
        return type(out)(_unwrap(o) for o in out)
    return out


def _wrap_inputs(args):
    """Split positional args into traced Tensors and STATIC values.

    Strings/objects that jnp.asarray rejects are closed over instead of
    traced (paddle's to_static passes non-tensor args through unchanged);
    since every call re-traces over concrete values (see the kernel NOTE
    below), each distinct static value simply steers its own trace.
    Returns (tensors, template) where template holds the static value per
    position, or _TENSOR_SLOT where a tensor goes.
    """
    tensors, template = [], []
    for a in args:
        if isinstance(a, Tensor):
            tensors.append(a)
            template.append(_TENSOR_SLOT)
        else:
            try:
                tensors.append(Tensor(jnp.asarray(a)))
                template.append(_TENSOR_SLOT)
            except (TypeError, ValueError):
                template.append(a)
    return tensors, template


_TENSOR_SLOT = object()


class StaticFunction:
    """Callable produced by @to_static: runs the layer as one traced XLA computation,
    differentiable through the eager tape (the computation appears as a single grad node)."""

    def __init__(self, layer=None, function=None, input_spec=None, build_strategy=None):
        self._layer = layer
        self._function = function
        self._jitted = None
        self._param_names = []

    def _build_kernel(self, template, kwargs):
        from . import dy2static

        layer = self._layer
        function = self._function
        param_names = self._param_names
        # AST conversion first (reference ProgramTranslator): tensor-dependent
        # if/while/for become lax.cond/while_loop so tracing succeeds
        raw = function or (layer.forward if layer is not None else None)
        converted = dy2static.convert_to_static(raw) if raw is not None else None

        # NOTE: this kernel intentionally closes over the raw kwargs DICT,
        # which core/dispatch._freeze cannot hash — so to_static programs are
        # NEVER rule-cached and re-trace per call over concrete values. That
        # is the semantic contract, not an accident: a cached (abstract)
        # trace would turn python control flow on input VALUES (`if flag:`,
        # `float(x)`) into abstract-tracer errors or silently different
        # programs. The reference ProgramTranslator re-traces per CacheKey
        # for the same reason.
        n_pos = len(template)
        statics = tuple((i, v) for i, v in enumerate(template)
                        if v is not _TENSOR_SLOT)

        def kernel(*arrays):
            param_arrays = arrays[:len(param_names)]
            input_arrays = iter(arrays[len(param_names):])
            # interleave traced tensors and static (closed-over) values back
            # into their original positions
            slots = dict(statics)
            inputs = [slots[i] if i in slots
                      else Tensor(next(input_arrays), stop_gradient=True)
                      for i in range(n_pos)]
            if layer is not None:
                state = dict(zip(param_names, param_arrays))
                with _swapped_state(layer, state), _tracing(), no_grad():
                    out = converted(*inputs, **kwargs)
            else:
                with _tracing(), no_grad():
                    out = converted(*inputs, **kwargs)
            return _unwrap(out)

        return kernel

    @property
    def code(self):
        from . import dy2static

        raw = self._function or (self._layer.forward if self._layer else None)
        return dy2static.get_code(raw)

    def __call__(self, *args, **kwargs):
        inputs, template = _wrap_inputs(args)
        if self._layer is not None:
            state = self._layer.state_dict(include_non_persistable_buffer=True)
            self._param_names = list(state.keys())
            tensor_args = [state[n] for n in self._param_names] + inputs
        else:
            tensor_args = inputs
        kernel = self._build_kernel(template, kwargs)
        return apply("to_static_program", kernel, tensor_args)


def to_static(layer_or_function=None, input_spec=None, build_strategy=None, **kwargs):
    from ..nn.layer import Layer

    def decorate(obj):
        if isinstance(obj, Layer):
            orig_forward = obj.forward
            obj.forward = StaticFunction(layer=obj, function=orig_forward)
            return obj
        bound_self = getattr(obj, "__self__", None)
        if isinstance(bound_self, Layer):
            # bound method of a Layer: its parameters must flow through the traced
            # program as inputs, or gradients silently stop at the jit boundary
            return StaticFunction(layer=bound_self, function=obj)
        import functools

        # plain function
        fn = StaticFunction(function=obj)
        functools.update_wrapper(fn, obj, updated=[])
        return fn

    if layer_or_function is None:
        return decorate
    return decorate(layer_or_function)


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer to a self-contained inference artifact.

    Reference: paddle.jit.save exports a ProgramDesc + params
    (python/paddle/fluid/dygraph/jit.py). TPU-native: the "program" is portable
    serialized StableHLO (jax.export) of the traced forward — loadable and
    runnable with NO model code, the same contract as the reference's saved
    inference model. Writes `{path}.pdmodel` (StableHLO), `{path}.pdiparams`
    (state dict), `{path}.pdmodel.meta` (json: input specs + param order).
    """
    import json

    from jax import export as jax_export

    from ..framework import io as fio
    from ..static import InputSpec

    if input_spec is None:
        raise ValueError("paddle.jit.save needs input_spec=[InputSpec(shape, "
                         "dtype)] (or example Tensors) to trace the forward")
    specs = []  # (shape with None preserved, dtype) — meta keeps the user intent
    for s in input_spec:
        if isinstance(s, InputSpec):
            shape = tuple(None if (d is None or d < 0) else d for d in s.shape)
            specs.append((shape, str(s.dtype)))
        elif isinstance(s, Tensor):
            specs.append((tuple(s.shape), str(s.dtype)))
        else:
            a = jnp.asarray(s)
            specs.append((tuple(a.shape), str(a.dtype)))

    state = layer.state_dict(include_non_persistable_buffer=True)
    param_names = list(state.keys())
    was_training = layer.training
    layer.eval()

    def fn(params_seq, *input_arrays):
        inner = dict(zip(param_names, params_seq))
        out = functional_call(layer, inner,
                              *[Tensor(a, stop_gradient=True)
                                for a in input_arrays])
        return _unwrap(out)

    try:
        from ..core.dtype import convert_dtype

        # dynamic dims (None/-1 in InputSpec) export as symbolic dimensions so
        # the artifact serves any size along them (paddle's variable-batch idiom)
        n_dyn = sum(d is None for sh, _ in specs for d in sh)
        sym_dims = iter(jax_export.symbolic_shape(
            ",".join(f"_dyn{i}" for i in range(n_dyn))) if n_dyn else ())
        arg_structs = [
            jax.ShapeDtypeStruct(
                tuple(next(sym_dims) if d is None else d for d in sh),
                convert_dtype(dt))
            for sh, dt in specs]
        param_structs = [jax.ShapeDtypeStruct(tuple(t.shape),
                                              convert_dtype(str(t.dtype)))
                         for t in state.values()]
        exported = jax_export.export(jax.jit(fn))(param_structs, *arg_structs)
    finally:
        if was_training:
            layer.train()

    with open(path + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    fio.save(state, path + ".pdiparams")
    with open(path + ".pdmodel.meta", "w") as f:
        json.dump({"param_names": param_names, "input_specs": specs}, f)


def load(path, **configs):
    """Load a jit.save artifact into a TranslatedLayer (inference-only, like the
    reference's paddle.jit.load of an inference model)."""
    return TranslatedLayer._load(path)


class TranslatedLayer:
    """Runs a serialized StableHLO program with its params. No model code needed;
    the analogue of the reference TranslatedLayer (dygraph/io.py)."""

    def __init__(self, exported, params, param_names, input_specs):
        self._exported = exported
        self._params = params  # name -> Tensor
        self._param_names = param_names
        self._input_specs = input_specs
        self.training = False

    @classmethod
    def _load(cls, path):
        import json

        from jax import export as jax_export

        from ..framework import io as fio

        with open(path + ".pdmodel", "rb") as f:
            exported = jax_export.deserialize(f.read())
        params = fio.load(path + ".pdiparams")
        with open(path + ".pdmodel.meta") as f:
            meta = json.load(f)
        params = {k: (v if isinstance(v, Tensor) else Tensor(jnp.asarray(v)))
                  for k, v in params.items()}
        return cls(exported, params, meta["param_names"], meta["input_specs"])

    def __call__(self, *inputs):
        arrays = [i._data if isinstance(i, Tensor) else jnp.asarray(i)
                  for i in inputs]
        param_seq = [self._params[n]._data for n in self._param_names]
        out = self._exported.call(param_seq, *arrays)
        if isinstance(out, (list, tuple)):
            return type(out)(Tensor(o, stop_gradient=True) for o in out)
        return Tensor(out, stop_gradient=True)

    forward = __call__

    def parameters(self, include_sublayers=True):
        return list(self._params.values())

    def state_dict(self, *a, **k):
        return dict(self._params)

    def set_state_dict(self, state_dict, use_structured_name=True):
        for k, v in state_dict.items():
            if k in self._params:
                self._params[k] = v if isinstance(v, Tensor) else Tensor(
                    jnp.asarray(v))

    def eval(self):
        self.training = False
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only (the exported "
                           "StableHLO program has no backward)")


def not_to_static(fn):
    return fn


def ignore_module(modules):
    pass


from . import dy2static  # noqa: E402,F401
from .dy2static import enable_to_static  # noqa: E402,F401


class ProgramTranslator:
    """Singleton switch parity (reference program_translator.py:775)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @classmethod
    def get_instance(cls):
        return cls()

    def enable(self, enable_to_static_flag: bool):
        enable_to_static(enable_to_static_flag)

    def get_code(self, dygraph_func):
        return dy2static.get_code(dygraph_func)


_code_level = 0


def set_code_level(level=100):
    """Log transformed code (reference dygraph_to_static logging_utils)."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    global _code_level
    _code_level = level


class TracedLayer:
    """Reference jit.TracedLayer: trace a dygraph layer with example inputs
    into a static program; here the trace is the StaticFunction program and
    save_inference_model reuses jit.save's StableHLO artifact."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._static = StaticFunction(layer=layer, function=layer.forward)
        self._example_inputs = inputs

    @staticmethod
    def trace(layer, inputs):
        traced = TracedLayer(layer, inputs)
        return traced(*inputs), traced

    def __call__(self, *args):
        return self._static(*args)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        from ..static import InputSpec

        specs = [InputSpec(list(t.shape), str(t.dtype)) for t in
                 self._example_inputs]
        save(self._layer, path, input_spec=specs)

"""Crash/NaN flight recorder: last-N records ring, dumped on trigger.

Black-box instrument for post-mortem debugging: while enabled it tees the
most recent StepTelemetry / serving records into a bounded in-memory ring
(no I/O on the hot path), and on a trigger writes everything it knows to a
fresh directory:

    <out_dir>/flight_<pid>_<seq>_<reason>/
        records.jsonl   the ring: last-N step/serve records, oldest first
        spans.json      recent tracer events (when the tracer is enabled)
        state.json      trigger metadata + core.monitor counters + metrics
                        registry snapshot (when metrics are active)

Triggers:
- dispatch NaN/Inf detection (`core.dispatch._check_nan_inf` calls
  `on_nan_inf()` right after bumping ``dispatch.nan_inf_hits``),
- an uncaught exception in `TrainStepEngine.step`/`run_steps` or the
  serving admit/decode loop (the engines dump before re-raising),
- an explicit `FlightRecorder.dump()`.

Enabled via ``PADDLE_TPU_FLIGHT_DIR`` (engines call `ensure_from_env()` at
construction) or programmatically via `enable(out_dir)`. Off by default:
`get()` returns None and the engines' per-step cost is one module-global
None check. NaN-triggered dumps are rate-limited (``nan_dump_limit``) so a
diverged run doesn't fill the disk with one dump per step.

Stdlib-only; no jax import on any path here.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import List, Optional

_DEFAULT_CAPACITY = 256
_SPAN_TAIL = 512


class FlightRecorder:
    def __init__(self, out_dir: str, capacity: int = _DEFAULT_CAPACITY,
                 span_tail: int = _SPAN_TAIL, nan_dump_limit: int = 2):
        self.out_dir = str(out_dir)
        self.capacity = int(capacity)
        self.span_tail = int(span_tail)
        self.nan_dump_limit = int(nan_dump_limit)
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dump_seq = 0
        self._nan_dumps = 0
        self.dumps: List[str] = []

    # ---- hot path ---------------------------------------------------------

    def record(self, rec: dict) -> None:
        """Tee one step/serve record into the ring (no I/O)."""
        with self._lock:
            self._ring.append(rec)

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # ---- triggers ---------------------------------------------------------

    def dump(self, reason: str, extra: Optional[dict] = None) -> str:
        """Write the ring + spans + counters to a fresh dump dir."""
        with self._lock:
            ring = list(self._ring)
            self._dump_seq += 1
            seq = self._dump_seq
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:60] or "manual"
        d = os.path.join(self.out_dir,
                         f"flight_{os.getpid()}_{seq:03d}_{safe}")
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "records.jsonl"), "w") as f:
            for rec in ring:
                f.write(json.dumps(rec, default=str) + "\n")
        spans = self._recent_spans()
        if spans is not None:
            with open(os.path.join(d, "spans.json"), "w") as f:
                json.dump(spans, f, default=str)
        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump(self._state(reason, extra), f, indent=2, sort_keys=True,
                      default=str)
        self.dumps.append(d)
        try:  # dump accounting in the metrics registry, by reason label
            from . import metrics as _metrics
            reg = _metrics.active_registry()
            if reg is not None:
                reg.counter("flight.dumps").inc()
                reg.counter("flight.dumps." + safe).inc()
        except ImportError:
            pass
        return d

    def on_nan_inf(self, source: str, extra: Optional[dict] = None
                   ) -> Optional[str]:
        """NaN/Inf trigger (rate-limited)."""
        with self._lock:
            if self._nan_dumps >= self.nan_dump_limit:
                return None
            self._nan_dumps += 1
        return self.dump(f"nan_inf_{source}", extra)

    # ---- dump contents ----------------------------------------------------

    def _recent_spans(self):
        try:
            from .tracer import get_tracer
        except ImportError:
            return None
        tr = get_tracer()
        if not tr.enabled:
            return None
        evs = tr.events()
        return evs[-self.span_tail:]

    def _state(self, reason, extra) -> dict:
        state = {
            "reason": reason,
            "time_unix": time.time(),
            "pid": os.getpid(),
            "ring_len": len(self._ring),
            "extra": extra or {},
        }
        try:
            from paddle_tpu.core import monitor
            state["counters"] = {name: dict(rep) for name, rep in
                                 sorted(monitor.registry().report().items())}
        except ImportError:
            pass
        try:
            from . import metrics as _metrics
            reg = _metrics.active_registry()
            if reg is not None:
                state["metrics"] = reg.snapshot(include_monitor=False,
                                                compact=True)
        except ImportError:
            pass
        try:
            # fleet context: the last collected fleet snapshot + router
            # placement tail when a collector/router is live — a crash
            # dump then shows the fleet, not just the dying process
            from . import fleet as _fleet
            fc = _fleet.flight_context()
            if fc:
                state.update(fc)  # "fleet" + "router_placements" keys
        except Exception:
            pass
        try:
            # training-health tail: the last decoded health records (grad
            # norms, nonfinite attribution) when a monitor is live — the
            # post-mortem context a health-triggered dump points at
            from . import health as _health
            hm = _health.get_monitor()
            if hm is not None:
                state["health_tail"] = hm.recent(32)
        except Exception:
            pass
        return state


# ---- process-global recorder (off until enabled) ---------------------------

_global: Optional[FlightRecorder] = None
_lock = threading.Lock()


def enable(out_dir: str, capacity: int = _DEFAULT_CAPACITY,
           **kw) -> FlightRecorder:
    global _global
    with _lock:
        if _global is None or _global.out_dir != str(out_dir):
            _global = FlightRecorder(out_dir, capacity=capacity, **kw)
        return _global


def disable() -> None:
    global _global
    with _lock:
        _global = None


def get() -> Optional[FlightRecorder]:
    """The recorder iff enabled, else None — the engines' hot-path gate."""
    return _global


def active() -> bool:
    return _global is not None


def ensure_from_env() -> Optional[FlightRecorder]:
    """Enable iff PADDLE_TPU_FLIGHT_DIR is set (idempotent)."""
    if _global is not None:
        return _global
    d = os.environ.get("PADDLE_TPU_FLIGHT_DIR")
    if not d:
        return None
    return enable(d)


def on_nan_inf(source: str, extra: Optional[dict] = None) -> Optional[str]:
    """Module-level NaN hook: no-op unless a recorder is enabled.

    `core.dispatch._check_nan_inf` calls this on its failure branch (after
    incrementing ``dispatch.nan_inf_hits``, before raising) — zero cost on
    the finite path, and only a None check when no recorder is attached.
    """
    fr = _global
    if fr is None:
        return None
    return fr.on_nan_inf(source, extra)

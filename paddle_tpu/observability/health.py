"""In-program training-health telemetry (ISSUE 8 tentpole).

The signals that predict a pod-scale run going sideways — the global grad
norm, per-parameter grad/weight norms and update-to-weight ratios, and
*which parameter first went non-finite* — are computed INSIDE the compiled
train step as one auxiliary output, riding the flat gradient buffer the
grad_comm accumulation path already builds (the cross-replica-sharding
paper's flat-buffer decomposition, arXiv:2004.13336, supplies the segment
map used for per-parameter attribution). The contract:

- **zero extra dispatches**: the stats are extra outputs of the SAME jitted
  step program (pinned by tests/test_health.py's HLO gates: one dispatch,
  one fused gradient all-reduce, unchanged by health);
- **at most one device->host transfer per FLAGS_health_interval steps**:
  everything is packed into ONE f32 ``[4P]`` buffer (P = parameter count)
  laid out as ``[grad_sq | weight_sq | update_sq | nonfinite_count]`` in
  flat-buffer segment order, fetched only on interval steps;
- **host-side attribution**: the first flat-buffer segment with a
  non-finite gradient is mapped back to the parameter NAME, fed to the
  metrics registry (``health.nonfinite.<param>``), written to the
  ``health.jsonl`` sink, and stamped into the flight-recorder dump that the
  breach triggers.

Segment boundaries come from ``segment_layout`` — sorted parameter names
with cumulative offsets, exactly the order ``ravel_pytree`` flattens a dict
(pinned by a test), so the per-segment stats computed from the grads dict
are literally per-slice stats of grad_comm's flat buffer.

Module-level imports stay stdlib-only (the observability posture); jax,
numpy, flags, and the monitor are imported lazily inside the methods that
need them.
"""
from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# log-spaced boundaries for norm/ratio histograms: grad norms and update
# ratios span many decades (1e-8 .. 1e6), unlike the default ms buckets
NORM_BUCKETS: Tuple[float, ...] = tuple(
    10.0 ** e for e in range(-8, 7))

_RING_CAPACITY = 64
_DUMP_LIMIT = 2  # per reason class, so a diverged run can't flood the disk


def segment_layout(param_shapes: Dict[str, Sequence[int]]
                   ) -> List[Tuple[str, int, int]]:
    """(name, flat_offset, size) per parameter, in flat-buffer order.

    Order is sorted-by-name — the order ``jax.flatten_util.ravel_pytree``
    flattens a dict and therefore the segment map of grad_comm's flat
    gradient buffer (tests pin the equivalence). Scalar params count as
    size 1.
    """
    out = []
    off = 0
    for name in sorted(param_shapes):
        size = 1
        for d in param_shapes[name]:
            size *= int(d)
        out.append((name, off, size))
        off += size
    return out


def _jf(x: float) -> Optional[float]:
    """JSON-safe float: finite values pass, inf/nan become None (the
    ``nonfinite_count`` field carries the signal)."""
    x = float(x)
    return x if math.isfinite(x) else None


class TrainingHealthMonitor:
    """Decodes the packed in-program health buffer and fans it out.

    The traced half (``make_packed_stats``) runs inside the compiled step;
    the host half (``on_step``) fetches the packed buffer every
    ``interval`` steps, decodes it against the segment layout, feeds the
    metrics registry (``train.grad_norm`` / ``train.weight_norm`` /
    ``train.update_ratio`` histograms), appends to the JSONL sink and the
    in-memory ring (the flight recorder's ``health_tail``), and triggers a
    flight-recorder dump on a grad-norm spike or a non-finite gradient —
    naming the offending parameter in both cases.
    """

    def __init__(self, param_shapes: Dict[str, Sequence[int]],
                 interval: Optional[int] = None,
                 spike_factor: Optional[float] = None,
                 sink=None, ring_capacity: int = _RING_CAPACITY):
        from ..core import flags as _flags

        self.segments = segment_layout(param_shapes)
        self.names = [s[0] for s in self.segments]
        self.packed_size = 4 * len(self.segments)
        self.interval = max(1, int(interval if interval is not None
                                   else _flags.flag("health_interval")))
        self.spike_factor = float(
            spike_factor if spike_factor is not None
            else _flags.flag("health_spike_factor"))
        self.sink = sink
        self._ring = collections.deque(maxlen=int(ring_capacity))
        self._lock = threading.Lock()
        self._ema: Optional[float] = None
        self._dumps: Dict[str, int] = {}
        _set_current(self)

    # ---- traced half (runs inside the compiled step) ----------------------

    def make_packed_stats(self) -> Callable:
        """Build the in-program stats fn: (grads, params, new_params) ->
        f32 [4P] packed buffer. Pure elementwise + per-segment reductions —
        no collectives, so the step's HLO collective shape is unchanged.
        Call with PRE-clip gradients (the true global mean grads; in the
        accumulation path these are slices of the flat buffer)."""
        names = list(self.names)

        def packed_stats(grads, params, new_params):
            import jax.numpy as jnp

            g2, w2, u2, nf = [], [], [], []
            for n in names:
                g = grads[n].astype(jnp.float32).ravel()
                w = params[n].astype(jnp.float32).ravel()
                d = new_params[n].astype(jnp.float32).ravel() - w
                g2.append(jnp.sum(g * g))
                w2.append(jnp.sum(w * w))
                u2.append(jnp.sum(d * d))
                nf.append(jnp.sum(~jnp.isfinite(g)).astype(jnp.float32))
            return jnp.stack(g2 + w2 + u2 + nf)

        return packed_stats

    def make_sharded_stats(self) -> Callable:
        """ZeRO twin of make_packed_stats for the weight-update-sharded step
        (distributed/grad_comm.make_zero_accum_step): (g_shard, p_shard,
        new_p_shard, seg_ids) -> f32 [4P] PARTIAL sums over one 1/N shard of
        the flat buffer. seg_ids maps each flat slot to its parameter
        ordinal in segment_layout order; pad slots carry ordinal P and fall
        into a dropped overflow segment. The partials ride the step's weight
        all-gather and are summed over replicas in-program, so the packed
        buffer the host decodes is layout-identical to the replicated
        path's — on_step/_ingest cannot tell the two apart."""
        p_count = len(self.segments)

        def sharded_stats(g_shard, p_shard, new_p_shard, seg_ids):
            import jax
            import jax.numpy as jnp

            d = new_p_shard - p_shard

            def seg(x):
                return jax.ops.segment_sum(
                    x, seg_ids, num_segments=p_count + 1)[:p_count]

            return jnp.concatenate([
                seg(g_shard * g_shard), seg(p_shard * p_shard), seg(d * d),
                seg((~jnp.isfinite(g_shard)).astype(jnp.float32))])

        return sharded_stats

    # ---- host half --------------------------------------------------------

    def wants(self, step: int) -> bool:
        return step % self.interval == 0

    def on_step(self, step: int, packed) -> Optional[dict]:
        """Interval-gated ingest: fetch the ONE packed buffer, decode, fan
        out. Off-interval steps cost one modulo — the device array is never
        touched, so no transfer happens."""
        if packed is None or not self.wants(step):
            return None
        return self._ingest(step, packed)

    def _ingest(self, step: int, packed) -> dict:
        import numpy as np

        from ..core import monitor as _monitor

        buf = np.asarray(packed, dtype=np.float64)  # the one D2H transfer
        _monitor.stat("health.fetches").increase()
        p = len(self.segments)
        g2, w2, u2, nf = buf[:p], buf[p:2 * p], buf[2 * p:3 * p], buf[3 * p:]
        nf_counts = np.nan_to_num(nf, nan=0.0, posinf=0.0).astype(np.int64)

        grad_norm = float(np.sqrt(g2.sum()))
        weight_norm = float(np.sqrt(w2.sum()))
        update_norm = float(np.sqrt(u2.sum()))
        update_ratio = update_norm / weight_norm if weight_norm > 0 else 0.0

        total_nf = int(nf_counts.sum())
        first_seg = first_param = None
        if total_nf:
            first_seg = int(np.argmax(nf_counts > 0))
            first_param = self.names[first_seg]

        per_param = {}
        for i, (name, _, _) in enumerate(self.segments):
            wn = math.sqrt(w2[i]) if math.isfinite(w2[i]) else math.inf
            un = math.sqrt(u2[i]) if math.isfinite(u2[i]) else math.inf
            per_param[name] = {
                "grad_norm": _jf(math.sqrt(g2[i]) if g2[i] >= 0
                                 else math.nan),
                "weight_norm": _jf(wn),
                "update_ratio": _jf(un / wn if wn > 0 else 0.0),
                "nonfinite": int(nf_counts[i]),
            }

        spike = (self.spike_factor > 0 and self._ema is not None
                 and math.isfinite(grad_norm)
                 and grad_norm > self.spike_factor * max(self._ema, 1e-30))
        rec = {
            "event": "health",
            "step": int(step),
            "ts": time.time(),
            "grad_norm": _jf(grad_norm),
            "weight_norm": _jf(weight_norm),
            "update_ratio": _jf(update_ratio),
            "nonfinite_count": total_nf,
            "first_nonfinite_param": first_param,
            "first_nonfinite_segment": first_seg,
            "spike": bool(spike),
            "per_param": per_param,
        }
        with self._lock:
            self._ring.append(rec)
        if self.sink is not None:
            self.sink.write(rec)
        self._feed_registry(rec)
        if total_nf:
            _monitor.stat("health.nonfinite_steps").increase()
            self._dump("health_nonfinite",
                       {"param": first_param, "segment": first_seg,
                        "step": int(step), "count": total_nf})
        if spike:
            _monitor.stat("health.spikes").increase()
            self._dump("health_grad_spike",
                       {"step": int(step), "grad_norm": grad_norm,
                        "ema": self._ema})
        if math.isfinite(grad_norm):
            self._ema = (grad_norm if self._ema is None
                         else 0.9 * self._ema + 0.1 * grad_norm)
        return rec

    def _feed_registry(self, rec: dict) -> None:
        from . import metrics as _metrics

        reg = _metrics.active_registry()
        if reg is None:
            return
        for field, hist in (("grad_norm", "train.grad_norm"),
                            ("weight_norm", "train.weight_norm"),
                            ("update_ratio", "train.update_ratio")):
            v = rec.get(field)
            if v is not None:  # non-finite values carry no distribution info
                reg.histogram(hist, boundaries=NORM_BUCKETS).observe(v)
        reg.gauge("health.last_step").set(rec["step"])
        if rec["nonfinite_count"]:
            reg.counter("health.nonfinite_steps").inc()
            reg.counter(
                "health.nonfinite." + rec["first_nonfinite_param"]).inc()
        if rec["spike"]:
            reg.counter("health.spikes").inc()

    def _dump(self, reason: str, extra: dict) -> Optional[str]:
        """Flight-recorder dump for a threshold breach, per-reason
        rate-limited. The dump's state.json carries the extra dict (which
        names the offending parameter) AND the health ring tail."""
        from . import flight_recorder as _flight

        fr = _flight.get()
        if fr is None:
            return None
        n = self._dumps.get(reason, 0)
        if n >= _DUMP_LIMIT:
            return None
        self._dumps[reason] = n + 1
        suffix = ""
        if extra.get("param"):
            suffix = "_" + str(extra["param"])
        return fr.dump(reason + suffix, extra)

    # ---- inspection -------------------------------------------------------

    def recent(self, n: int = 32) -> List[dict]:
        """Most recent decoded health records, oldest first (the flight
        recorder embeds this as ``health_tail`` in state.json dumps)."""
        with self._lock:
            recs = list(self._ring)
        return recs[-int(n):]

    def close(self) -> None:
        if self.sink is not None:
            self.sink.close()


# ---- process-global current monitor (for the flight recorder) --------------

_current: Optional[TrainingHealthMonitor] = None
_glock = threading.Lock()


def _set_current(m: TrainingHealthMonitor) -> None:
    global _current
    with _glock:
        _current = m


def get_monitor() -> Optional[TrainingHealthMonitor]:
    """The most recently constructed monitor, or None — what the flight
    recorder asks for when assembling a state.json health tail."""
    return _current


def reset() -> None:
    """Drop the global monitor reference (test isolation)."""
    global _current
    with _glock:
        _current = None


def from_env_or_flags(param_shapes: Dict[str, Sequence[int]]
                      ) -> Optional[TrainingHealthMonitor]:
    """Monitor iff FLAGS_health_monitor or PADDLE_TPU_HEALTH_DIR is set,
    else None — the engines' zero-cost construction probe. The env var also
    attaches a ``health.jsonl`` JsonlSink in that directory."""
    import os

    from ..core import flags as _flags

    d = os.environ.get("PADDLE_TPU_HEALTH_DIR")
    if not d and not _flags.flag("health_monitor"):
        return None
    sink = None
    if d:
        from .step_telemetry import JsonlSink

        sink = JsonlSink(os.path.join(d, "health.jsonl"))
    return TrainingHealthMonitor(param_shapes, sink=sink)

"""Closed-loop capacity controller: SLO burn + load signals -> replica count.

The last arc of the observe/act loop: PR 15's burn-rate alerts *observe*,
the membership/router drain machinery *acts*, and this module decides.
``CapacityController.poll()`` reads the current signal set — firing SLO
alerts (local engine or the fleet-merged one a FleetCollector evaluates),
mean slot occupancy, queued-requests-per-slot — computes a target replica
count, and drives the difference through the ReplicaRouter:

- **scale out** when a page/warn alert is firing, or occupancy / queue
  depth stay above the high-water marks for ``high_sustain_s``
  (target = ceil(current * scale_out_factor), clamped to max_replicas);
- **scale in** when nothing is firing, every SLO retains at least
  ``budget_min`` of its error budget, and the fleet sits idle
  (occupancy/queue below the low-water marks) for ``idle_sustain_s``
  (target = floor(current / scale_in_factor), clamped to min_replicas);
- **hysteresis / flap damping**: distinct high/low water marks, sustain
  windows on both directions, and a ``cooldown_s`` dead time after every
  action — a spike that resolves mid-cooldown cannot bounce the fleet.

Scale-out spawns replicas via the injected ``spawn(name) -> engine``
factory (only the application knows how to build one), adds them to the
router, and registers a membership lease when a store is attached.
Scale-in uses the router's drain protocol — ``begin_drain`` re-places
queued work on survivors, later polls reap fully drained replicas via
``remove_replica`` (which releases the lease) — so no request is ever
lost to a scaling decision.

Every decision is first-class evidence: a ``capacity.decide`` span (with
``capacity.scale_out`` / ``capacity.scale_in`` children pointing back at
it) when the tracer is on, and one ``capacity.jsonl`` record carrying the
full input-signal snapshot that justified it, rendered as a scaling
timeline by tools/trace_summary.py and served live at the exporter's
``/capacity`` route.

Dark by default: nothing is installed at import, ``poll()`` only runs
when called (or via ``start()``'s daemon loop), and with no registry /
tracer / jsonl path a poll touches none of them. This module never
imports jax, serving, or distributed — the router, spawn factory, and
store are injected and duck-typed (observability stays import-light).
"""
from __future__ import annotations

import collections
import json
import math
import threading
import time
from typing import Callable, Dict, List, Optional

from . import metrics as _metrics
from . import tracer as _tracer


class CapacityPolicy:
    """Scaling policy knobs (see module doc for the decision rules)."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 occupancy_high: float = 0.85, occupancy_low: float = 0.15,
                 queue_high: float = 2.0, queue_low: float = 0.25,
                 high_sustain_s: float = 0.0, idle_sustain_s: float = 2.0,
                 cooldown_s: float = 5.0, budget_min: float = 0.25,
                 scale_out_factor: float = 2.0,
                 scale_in_factor: float = 2.0):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]")
        if scale_out_factor <= 1.0 or scale_in_factor <= 1.0:
            raise ValueError("scale factors must be > 1.0")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.occupancy_high = float(occupancy_high)
        self.occupancy_low = float(occupancy_low)
        self.queue_high = float(queue_high)    # queued requests per slot
        self.queue_low = float(queue_low)
        self.high_sustain_s = float(high_sustain_s)
        self.idle_sustain_s = float(idle_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.budget_min = float(budget_min)    # min error budget to shrink
        self.scale_out_factor = float(scale_out_factor)
        self.scale_in_factor = float(scale_in_factor)

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "min_replicas", "max_replicas", "occupancy_high",
            "occupancy_low", "queue_high", "queue_low", "high_sustain_s",
            "idle_sustain_s", "cooldown_s", "budget_min",
            "scale_out_factor", "scale_in_factor")}


class CapacityController:
    """Poll signals, decide a target replica count, drive the router.

    router: a serving.ReplicaRouter (duck-typed: live_replicas /
    add_replica / begin_drain / drained / remove_replica / replicas).
    spawn(name) -> ServingEngine builds a new replica (the application
    owns model/engine construction). slo_engine: the SloEngine whose
    firing alerts / error budgets gate scaling — pass the same engine a
    FleetCollector.attach_slo holds and the judgement is fleet-merged.
    collector: optional FleetCollector; when set, each poll runs a
    collect() first so the SLO state reflects the whole fleet, not just
    this process. store/lease_s: membership wiring for spawned replicas
    (engine.register_replica) — None skips it (single-process drills).
    jsonl_path: capacity.jsonl decision log. clock: injectable time
    source for tests.
    """

    def __init__(self, router, spawn: Callable[[str], object],
                 policy: Optional[CapacityPolicy] = None, slo_engine=None,
                 collector=None, store=None, lease_s: Optional[float] = None,
                 jsonl_path: Optional[str] = None, name_prefix: str = "r",
                 log_holds: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.router = router
        self.spawn = spawn
        self.policy = policy or CapacityPolicy()
        self.slo_engine = slo_engine
        self.collector = collector
        self.store = store
        self.lease_s = lease_s
        self.jsonl_path = jsonl_path
        self.name_prefix = str(name_prefix)
        self.log_holds = bool(log_holds)
        self.clock = clock
        self.polls = 0
        self.scale_outs = 0
        self.scale_ins = 0
        self.last_decision: Optional[dict] = None
        self.decisions: collections.deque = collections.deque(maxlen=256)
        self._retiring: Dict[str, float] = {}     # name -> drain start
        self._last_action_ts: Optional[float] = None
        self._high_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._next_index = self._seed_index()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _seed_index(self) -> int:
        idx = 0
        for name in self.router.replicas:
            tail = name[len(self.name_prefix):] \
                if name.startswith(self.name_prefix) else ""
            if tail.isdigit():
                idx = max(idx, int(tail) + 1)
        return max(idx, len(self.router.replicas))

    # -------------------------------------------------------------- signals
    def _signals(self) -> dict:
        live = self.router.live_replicas()
        occ = [e.occupancy() for e in live.values()]
        queued = sum(e.queue_depth() for e in live.values())
        slots = sum(e.slot_count for e in live.values())
        firing: List[dict] = []
        budget_remaining = 1.0
        if self.collector is not None:
            # a poll is a federation pass: the merged snapshot feeds the
            # attached SLO engine, so `firing` below is fleet-level truth
            self.collector.collect()
        if self.slo_engine is not None:
            firing = [{"slo": a["slo"], "severity": a["severity"],
                       "labels": a.get("labels") or {}}
                      for a in self.slo_engine.firing()]
            results = self.slo_engine.last_results
            if results:
                budget_remaining = min(r["budget_remaining"]
                                       for r in results)
        return {
            "replicas": len(live),
            "retiring": sorted(self._retiring),
            "occupancy": round(sum(occ) / len(occ), 4) if occ else 0.0,
            "queued": queued,
            "queue_per_slot": round(queued / slots, 4) if slots else 0.0,
            "firing": firing,
            "budget_remaining": round(budget_remaining, 4),
        }

    # ------------------------------------------------------------- the loop
    def poll(self, now: Optional[float] = None) -> dict:
        """One decide(+act) pass; returns the decision record. Thread-safe
        against concurrent /capacity scrapes (doc() takes the same lock)."""
        with self._lock:
            return self._poll_locked(now)

    def _poll_locked(self, now: Optional[float]) -> dict:
        now = self.clock() if now is None else float(now)
        tr = _tracer.get_tracer()
        t0 = time.perf_counter() if tr.enabled else None
        self._reap()
        sig = self._signals()
        pol = self.policy
        cur = sig["replicas"]
        action, reason, target = "hold", "steady", cur

        hot = (sig["occupancy"] >= pol.occupancy_high
               or sig["queue_per_slot"] >= pol.queue_high)
        idle = (sig["occupancy"] <= pol.occupancy_low
                and sig["queue_per_slot"] <= pol.queue_low)
        # explicit None checks: a sustain clock started at t=0.0 is falsy
        if hot:
            self._high_since = now if self._high_since is None \
                else self._high_since
        else:
            self._high_since = None
        if idle:
            self._idle_since = now if self._idle_since is None \
                else self._idle_since
        else:
            self._idle_since = None
        in_cooldown = (self._last_action_ts is not None
                       and now - self._last_action_ts < pol.cooldown_s)

        want_out = bool(sig["firing"]) or (
            hot and now - self._high_since >= pol.high_sustain_s)
        want_in = (not sig["firing"] and not self._retiring
                   and sig["budget_remaining"] >= pol.budget_min
                   and idle
                   and now - self._idle_since >= pol.idle_sustain_s)

        if want_out and cur < pol.max_replicas and not in_cooldown:
            target = min(pol.max_replicas,
                         max(cur + 1,
                             math.ceil(cur * pol.scale_out_factor)))
            action = "scale_out"
            reason = ("slo_burn" if sig["firing"] else
                      "occupancy" if sig["occupancy"] >= pol.occupancy_high
                      else "queue_depth")
        elif want_in and cur > pol.min_replicas and not in_cooldown:
            target = max(pol.min_replicas,
                         min(cur - 1,
                             math.floor(cur / pol.scale_in_factor)))
            action = "scale_in"
            reason = "idle_budget"
        elif (want_out or want_in) and in_cooldown:
            reason = "cooldown"

        span_id = _tracer.new_span_id() if tr.enabled else None
        if action == "scale_out":
            added = self._scale_out(target - cur, span_id)
            self.scale_outs += 1
            self._last_action_ts = now
            self._high_since = None
        elif action == "scale_in":
            drained = self._scale_in(cur - target, now, span_id)
            self.scale_ins += 1
            self._last_action_ts = now
            self._idle_since = None
        rec = {
            "event": "capacity", "ts": time.time(), "action": action,
            "reason": reason, "replicas": cur, "target": target,
            "signals": sig,
        }
        if action == "scale_out":
            rec["added"] = added
        elif action == "scale_in":
            rec["draining"] = drained
        self.polls += 1
        self.last_decision = rec
        self.decisions.append(rec)
        if tr.enabled:
            tr.record_complete("capacity.decide", t0, time.perf_counter(), {
                "span_id": span_id, "action": action, "reason": reason,
                "replicas": cur, "target": target,
                "occupancy": sig["occupancy"],
                "queue_per_slot": sig["queue_per_slot"],
                "firing": len(sig["firing"]),
            })
        mreg = _metrics.active_registry()
        if mreg is not None:
            mreg.gauge("capacity.replicas").set(float(cur))
            mreg.gauge("capacity.target_replicas").set(float(target))
            mreg.gauge("capacity.retiring").set(float(len(self._retiring)))
            if action == "scale_out":
                mreg.counter("capacity.scale_outs").inc()
            elif action == "scale_in":
                mreg.counter("capacity.scale_ins").inc()
        if self.jsonl_path and (action != "hold" or self.log_holds):
            try:
                with open(self.jsonl_path, "a") as f:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
            except OSError:
                pass
        return rec

    # -------------------------------------------------------------- actions
    def _scale_out(self, n: int, parent_span: Optional[int]) -> List[str]:
        tr = _tracer.get_tracer()
        added = []
        for _ in range(n):
            name = f"{self.name_prefix}{self._next_index}"
            self._next_index += 1
            t0 = time.perf_counter() if tr.enabled else None
            eng = self.spawn(name)
            self.router.add_replica(name, eng)
            if self.store is not None:
                eng.register_replica(self.store, name, lease_s=self.lease_s)
            if tr.enabled:
                tr.record_complete(
                    "capacity.scale_out", t0, time.perf_counter(),
                    {"replica": name, "parent_span": parent_span})
            added.append(name)
        return added

    def _scale_in(self, n: int, now: float,
                  parent_span: Optional[int]) -> List[str]:
        # retire the most-recently-added live replicas first (reverse
        # add order): the original fleet keeps its warm caches
        tr = _tracer.get_tracer()
        live = [name for name, e in self.router.live_replicas().items()]
        victims = list(reversed(live))[:n]
        for name in victims:
            t0 = time.perf_counter() if tr.enabled else None
            replaced = self.router.begin_drain(name, reason="capacity")
            self._retiring[name] = now
            if tr.enabled:
                tr.record_complete(
                    "capacity.scale_in", t0, time.perf_counter(),
                    {"replica": name, "replaced": len(replaced),
                     "parent_span": parent_span})
        return victims

    def _reap(self) -> None:
        """Remove retiring replicas whose drain has completed (their
        active slots finished under the shared drive loop)."""
        for name in [n for n in self._retiring
                     if n in self.router.replicas
                     and self.router.drained(n)]:
            self.router.remove_replica(name)
            del self._retiring[name]
        # a retiring name no longer in the router was removed externally
        for name in [n for n in self._retiring
                     if n not in self.router.replicas]:
            del self._retiring[name]

    # ----------------------------------------------------- background loop
    def start(self, interval_s: float = 1.0) -> "CapacityController":
        """Poll on a daemon thread every interval_s (production mode; the
        drills call poll() inline from their drive loops)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:
                    pass  # a signal-read hiccup must not kill the loop

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle-tpu-capacity")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None

    # ---------------------------------------------------------------- views
    def doc(self) -> dict:
        """The /capacity document: policy, live state, decision tail."""
        with self._lock:
            return {
                "policy": self.policy.as_dict(),
                "replicas": sorted(self.router.replicas),
                "live": sorted(self.router.live_replicas()),
                "retiring": sorted(self._retiring),
                "polls": self.polls,
                "scale_outs": self.scale_outs,
                "scale_ins": self.scale_ins,
                "last": self.last_decision,
                "decisions": list(self.decisions)[-32:],
            }


# ---- process-global controller (dark until installed) -----------------------

_controller: Optional[CapacityController] = None
_glock = threading.Lock()


def install_controller(controller: CapacityController) -> CapacityController:
    """Install the process-global controller — the exporter's /capacity
    route serves it once present."""
    global _controller
    with _glock:
        _controller = controller
        return _controller


def uninstall_controller() -> None:
    global _controller
    with _glock:
        if _controller is not None:
            _controller.stop()
        _controller = None


def active_controller() -> Optional[CapacityController]:
    """The installed controller, else None (the exporter's /capacity gate)."""
    return _controller

"""Per-step training telemetry: one structured record per optimizer step.

The run-time complement of the offline probes in tools/ (step_breakdown,
mxu_roofline): instead of re-deriving throughput after the fact, the
training loop itself emits a JSONL stream of step records — wall time,
tokens/s, achieved TFLOP/s, estimated MFU (flops.py model, the bench.py
convention), device-memory high-water, and the compile/dispatch counters
from core.monitor — through a pluggable sink. The reference analogue is the
benchmark/profiler timer feeding ips into logs (profiler/timer.py), grown
into a machine-readable stream tools/trace_summary.py can tabulate.

Disabled-path contract (asserted by tests/test_profiler.py): when no
telemetry is attached nothing here runs — no jax import, no file I/O, no
sync. This module itself imports only stdlib; device stats are fetched
lazily inside record_step.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional


class InMemorySink:
    """Collects records in a list — for tests and notebook inspection."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def write(self, record: Dict[str, Any]) -> None:
        self.records.append(record)

    def close(self) -> None:
        pass


class JsonlSink:
    """Appends one JSON line per record; opened lazily, flushed per write so
    a crashed run keeps every completed step."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def write(self, record: Dict[str, Any]) -> None:
        if self._f is None:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            self._f = open(self.path, "a")
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class StepTelemetry:
    """Builds and emits per-step records.

    flops_per_token: model-FLOPs per trained token (see
        flops.transformer_flops_per_token); enables tflops_per_sec and mfu.
    peak_flops: MFU denominator in FLOP/s; defaults per backend at first
        record (flops.peak_flops_per_sec), None on backends with no
        calibrated peak — mfu is then omitted.
    """

    def __init__(self, sink=None, flops_per_token: Optional[int] = None,
                 peak_flops: Optional[float] = None,
                 collect_memory: bool = True,
                 collect_live_buffers: bool = False):
        self.sink = sink if sink is not None else InMemorySink()
        self.flops_per_token = flops_per_token
        self.peak_flops = peak_flops
        self.collect_memory = collect_memory
        # live-buffer census (count + bytes of live jax arrays): the
        # donation high-water proof on backends without PJRT memory stats
        # (CPU test mesh). O(live arrays) per record — opt-in.
        self.collect_live_buffers = collect_live_buffers
        self._records = 0
        self._live_high_water = 0
        self._last_counters: Dict[str, int] = {}

    # ---- construction helpers ----
    @classmethod
    def from_env(cls, **kw) -> Optional["StepTelemetry"]:
        """JsonlSink telemetry when PADDLE_TPU_TELEMETRY_DIR is set, else
        None (the cheap probe callers use to stay zero-cost when off)."""
        d = os.environ.get("PADDLE_TPU_TELEMETRY_DIR")
        if not d:
            return None
        return cls(sink=JsonlSink(os.path.join(d, "step_telemetry.jsonl")),
                   **kw)

    def set_flop_model(self, flops_per_token: int,
                       peak_flops: Optional[float] = None) -> None:
        self.flops_per_token = flops_per_token
        if peak_flops is not None:
            self.peak_flops = peak_flops

    # ---- emission ----
    def record_step(self, *, step: int, wall_time: float,
                    samples: Optional[int] = None,
                    tokens: Optional[int] = None,
                    loss: Optional[float] = None,
                    reader_cost: Optional[float] = None,
                    h2d_ms: Optional[float] = None,
                    prefetch_depth: Optional[int] = None,
                    microbatches: Optional[int] = None,
                    grad_comm_dtype: Optional[str] = None,
                    grad_comm_bytes: Optional[int] = None,
                    phase: str = "train",
                    extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Emit one record; returns it (tests read the return directly)."""
        rec: Dict[str, Any] = {
            "event": f"{phase}_step",
            "step": int(step),
            "ts": time.time(),
            "wall_time_s": round(wall_time, 6),
        }
        if loss is not None:
            rec["loss"] = float(loss)
        if reader_cost is not None:
            rec["reader_cost_s"] = round(reader_cost, 6)
        if h2d_ms is not None:
            # host->device staging: the batch's sharded device_put issue wall
            # time (async dispatch — issue cost, not transfer completion)
            rec["h2d_ms"] = round(h2d_ms, 3)
        if prefetch_depth is not None:
            # look-ahead the consumer actually had when this batch was taken
            rec["prefetch_depth"] = int(prefetch_depth)
        if microbatches is not None:
            # in-program gradient accumulation (distributed/grad_comm.py):
            # K microbatches per optimizer step, ONE dispatch
            rec["microbatches"] = int(microbatches)
        if grad_comm_dtype is not None:
            rec["grad_comm_dtype"] = str(grad_comm_dtype)
        if grad_comm_bytes is not None:
            # per-device payload handed to the gradient collective — the
            # number the low-precision dtypes shrink
            rec["grad_comm_bytes"] = int(grad_comm_bytes)
        if samples is not None:
            rec["samples"] = int(samples)
            rec["samples_per_sec"] = round(samples / max(wall_time, 1e-9), 2)
        if tokens is not None:
            rec["tokens"] = int(tokens)
            tps = tokens / max(wall_time, 1e-9)
            rec["tokens_per_sec"] = round(tps, 1)
            if self.flops_per_token:
                fps = self.flops_per_token * tps
                rec["tflops_per_sec"] = round(fps / 1e12, 3)
                peak = self._resolve_peak()
                if peak:
                    rec["mfu"] = round(fps / peak, 4)
        rec.update(self._counter_deltas())
        if self.collect_memory:
            # always present so consumers see a stable shape; {} on backends
            # where PJRT exposes no memory stats (the CPU test mesh)
            rec["device_memory"] = self._memory_stats()
        if self.collect_live_buffers:
            lb = self._live_buffers()
            if lb:
                self._live_high_water = max(self._live_high_water,
                                            lb["bytes"])
                lb["high_water_bytes"] = self._live_high_water
                rec["live_buffers"] = lb
        if extra:
            rec.update(extra)
        self.sink.write(rec)
        self._records += 1
        return rec

    def close(self) -> None:
        self.sink.close()

    # ---- internals ----
    def _resolve_peak(self) -> Optional[float]:
        if self.peak_flops is not None:
            return self.peak_flops
        try:
            import jax

            from . import flops as _flops

            self.peak_flops = _flops.peak_flops_per_sec(jax.default_backend())
        except Exception:
            self.peak_flops = None
        return self.peak_flops

    def _counter_deltas(self) -> Dict[str, Any]:
        """Compile/dispatch counters from core.monitor: running totals plus
        the delta since the previous record (a nonzero jit_compiles_delta
        mid-run IS the recompile alarm)."""
        from ..core import monitor

        out: Dict[str, Any] = {}
        rep = monitor.registry().report()
        for key, field in (("engine.jit_compiles", "jit_compiles"),
                           ("engine.jit_compile_ms", "jit_compile_ms"),
                           ("engine.jit_recompiles", "jit_recompiles"),
                           # persistent-compilation-cache split: cold paid
                           # XLA, warm deserialized from the store
                           # (core/compile_cache.py) — a restarted process
                           # with a warm cache shows compile_warm_ms only
                           ("engine.compile_cold", "compile_cold"),
                           ("engine.compile_cold_ms", "compile_cold_ms"),
                           ("engine.compile_warm", "compile_warm"),
                           ("engine.compile_warm_ms", "compile_warm_ms"),
                           # gradient-communication subsystem
                           # (distributed/grad_comm.py): accumulated steps,
                           # microbatches, and collective payload bytes
                           ("grad_comm.steps", "grad_comm_steps"),
                           ("grad_comm.microbatches",
                            "grad_comm_microbatches"),
                           ("grad_comm.bytes_moved", "grad_comm_bytes_moved"),
                           ("grad_comm.lowp_steps", "grad_comm_lowp_steps"),
                           # ZeRO weight-update sharding: bytes handed to
                           # the gradient reduce-scatter / weight all-gather
                           ("grad_comm.rs_bytes", "grad_comm_rs_bytes"),
                           ("grad_comm.ag_bytes", "grad_comm_ag_bytes"),
                           ("dispatch.calls", "dispatch_calls"),
                           ("dispatch.nan_inf_hits", "nan_inf_hits"),
                           # decode/serving executables (models/gpt.py LRU
                           # + serving/engine.py): compile growth here mid-
                           # serve means something re-keyed on prompt shape
                           ("decode.jit_compiles", "decode_jit_compiles"),
                           ("decode.cache_evictions",
                            "decode_cache_evictions"),
                           ("serving.prefill_compiles",
                            "serving_prefill_compiles"),
                           ("serving.decode_compiles",
                            "serving_decode_compiles"),
                           ("serving.steps", "serving_steps"),
                           ("serving.tokens", "serving_tokens")):
            if key in rep:
                v = rep[key]["value"]
                out[field] = v
                delta = v - self._last_counters.get(key, 0)
                if field in ("jit_compiles", "jit_recompiles") and delta:
                    out[field + "_delta"] = delta
                self._last_counters[key] = v
        return out

    def _live_buffers(self) -> Dict[str, int]:
        try:
            from ..core import monitor

            return dict(monitor.live_buffer_stats())
        except Exception:
            return {}

    def _memory_stats(self) -> Dict[str, int]:
        try:
            from ..core import monitor

            stats = monitor.device_memory_stats()
        except Exception:
            return {}
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")
        return {k: int(stats[k]) for k in keep if k in stats}

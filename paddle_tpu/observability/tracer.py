"""Host-side span tracer: the framework's always-available timeline recorder.

Replaces the profiler's aggregate-only ``_event_stats`` dict with a real
event stream: every span keeps (name, ts, dur, tid, args) in a bounded ring
buffer and exports genuine chrome-trace JSON (``trace_events`` format), so
host markers can be loaded into Perfetto/chrome://tracing next to the
``jax.profiler`` device timeline. The reference analogue is
HostEventRecorder + the chrome-trace serializer in
paddle/fluid/platform/profiler/chrometracing_logger.cc.

Two-tier cost model (the subsystem is meant to stay ON in production):

- aggregates (count/total/max/min per span name) are ALWAYS maintained —
  a dict update per span end, the same cost the old ``_event_stats`` paid;
- full events are recorded ONLY while ``enable()`` is active, into a
  fixed-capacity ring buffer (old events are dropped, memory is bounded);
- when tracing is disabled, ``span()`` returns a shared no-op context
  manager: no timestamp is taken, no allocation, no I/O, and this module
  never imports jax.

Thread safety: one lock guards the ring buffer and the aggregate table;
span objects themselves are not shared across threads (each ``span()`` call
makes its own). tid is the OS thread ident so nested spans from different
threads land on separate chrome-trace rows.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional

# chrome trace wants microseconds; all internal timestamps are seconds from
# the process-wide origin below so exported traces from one process align.
_ORIGIN = time.perf_counter()


class _NullSpan:
    """Shared disabled-path context manager: enter/exit do nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """RAII span bound to one tracer; records a complete event on exit."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def end(self):
        if self._t0 is None:
            return
        t1 = time.perf_counter()
        self._tracer.record_complete(self.name, self._t0, t1, self.args)
        self._t0 = None


class Tracer:
    def __init__(self, capacity: int = 100_000):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._stats: Dict[str, list] = {}  # name -> [count, total, max, min]
        self.enabled = False
        self._dropped = 0

    # ---- control ----
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def clear_stats(self) -> None:
        with self._lock:
            self._stats.clear()

    # ---- recording ----
    def span(self, name: str, **args):
        """Context manager timing a region. Free when tracing is disabled
        AND no aggregate is wanted — aggregates come from explicit
        RecordEvent/record_complete callers, so the fast path here is a
        single attribute check."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def record_complete(self, name: str, t0: float, t1: float,
                        args: Optional[dict] = None,
                        tid: Optional[int] = None,
                        aggregate: bool = True) -> None:
        """Record a finished [t0, t1] perf_counter interval."""
        dur = t1 - t0
        with self._lock:
            if aggregate:
                st = self._stats.get(name)
                if st is None:
                    st = self._stats[name] = [0, 0.0, 0.0, float("inf")]
                st[0] += 1
                st[1] += dur
                if dur > st[2]:
                    st[2] = dur
                if dur < st[3]:
                    st[3] = dur
            if self.enabled:
                if len(self._events) == self._events.maxlen:
                    self._dropped += 1
                self._events.append((name, t0 - _ORIGIN, dur,
                                     tid if tid is not None
                                     else threading.get_ident(), args))

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (chrome-trace 'i' event)."""
        if not self.enabled:
            return
        t = time.perf_counter()
        with self._lock:
            self._events.append((name, t - _ORIGIN, None,
                                 threading.get_ident(), args or None))

    # ---- inspection / export ----
    def events(self) -> List[dict]:
        """Snapshot of buffered events as dicts (ts/dur in seconds)."""
        with self._lock:
            return [
                {"name": n, "ts": ts, "dur": dur, "tid": tid,
                 **({"args": args} if args else {})}
                for n, ts, dur, tid, args in self._events
            ]

    def stats(self) -> Dict[str, list]:
        """name -> [count, total_s, max_s, min_s] aggregate table."""
        with self._lock:
            return {n: list(v) for n, v in self._stats.items()}

    @property
    def dropped(self) -> int:
        return self._dropped

    def chrome_trace(self, process_name: str = "paddle_tpu host") -> dict:
        """The buffered timeline in chrome-trace ``trace_events`` format
        (complete 'X' events in microseconds), ready to json.dump or to
        merge with a jax.profiler perfetto export."""
        pid = os.getpid()
        trace_events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }]
        with self._lock:
            for name, ts, dur, tid, args in self._events:
                ev = {"name": name, "pid": pid, "tid": tid,
                      "ts": round(ts * 1e6, 3)}
                if dur is None:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                else:
                    ev["ph"] = "X"
                    ev["dur"] = round(dur * 1e6, 3)
                if args:
                    ev["args"] = dict(args)
                trace_events.append(ev)
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        """Write the chrome trace JSON to ``path`` and return the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


_global_tracer = Tracer()

_span_ids = itertools.count(1)


def new_span_id() -> int:
    """Process-unique id for cross-component span parentage (fleet trace
    context): the router mints one per placement span; engine-side child
    spans carry it as ``parent_span`` so one chrome trace links routing
    decision -> queue wait -> prefill/decode for a single request."""
    return next(_span_ids)


def get_tracer() -> Tracer:
    return _global_tracer


def enabled() -> bool:
    return _global_tracer.enabled


def span(name: str, **args):
    """Module-level sugar over the global tracer."""
    return _global_tracer.span(name, **args)

"""Shared model-FLOPs accounting for throughput/MFU telemetry.

One home for the convention bench.py and tools/mxu_roofline.py already use
(PaLM appendix B): 6*N parameter FLOPs per token plus the full causal
attention matmul term 12*L*h*s. StepTelemetry, bench, and the offline tools
must all divide by the same number or cross-checking them is meaningless.
"""
from __future__ import annotations

# Datasheet bf16 peak per chip, matching bench.py's MFU denominator.
PEAK_TFLOPS = {"tpu": 197.0}  # v5e bf16


def transformer_flops_per_token(n_params: int, num_layers: int = 0,
                                hidden_size: int = 0, seq_len: int = 0) -> int:
    """Training FLOPs per token: 6*N (fwd + 2x bwd over every parameter)
    plus the attention-matmul term. Counts FULL attention matmuls even when
    a causal flash kernel skips ~half the blocks — same deliberate choice as
    bench.py so MFU series stay comparable."""
    return 6 * n_params + 12 * num_layers * hidden_size * seq_len


def peak_flops_per_sec(backend: str) -> float | None:
    """Per-chip peak in FLOP/s for the MFU denominator; None when the
    backend has no calibrated datasheet number (e.g. the CPU test mesh)."""
    tf = PEAK_TFLOPS.get(backend)
    return tf * 1e12 if tf is not None else None

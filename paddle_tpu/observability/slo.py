"""Declarative SLOs: multi-window burn-rate alerting over the registry.

The judgement layer on top of the raw signal plane (PRs 6/7/14): an
`SloSpec` declares a service-level indicator plus objective, a
`SnapshotRing` over `metrics.MetricRegistry.snapshot()` documents gives
exact sliding-window deltas (the subtraction dual of the fleet merge
math — `metrics.subtract_registry_snapshots`), and `SloEngine.tick()`
evaluates every spec with the standard SRE multi-window multi-burn-rate
recipe, driving an `AlertManager` state machine whose transitions feed
every consumer the plane already has:

- ``slo.<name>.burn_rate`` / ``.error_budget_remaining`` /
  ``.firing`` gauges back into the registry (scraped at /metrics),
- an ``alerts.jsonl`` sink (one line per pending/firing/resolved
  transition — `tools/trace_summary.py` renders the timeline),
- rate-limited flight-recorder dumps on page-severity fires,
- exporter routes: ``GET /alerts`` (full alert/spec state) and the
  upgraded ``GET /healthz`` (503 + ``{"status": "degraded"}`` while a
  page-severity alert fires),
- self-healing hooks (`add_hook`): `serving.router.ReplicaRouter
  .attach_slo` sheds (and can drain) a replica whose per-replica SLO
  fires; `distributed.membership.ElasticCoordinator.note_alert` annotates
  reformation postmortems.

SLI forms:

- **ratio** (`ratio_slo`): bad-events / total-events counters over the
  window — e.g. ``serve.errors / serve.requests`` with objective 0.999.
  Names resolve against the snapshot's counters, then the absorbed
  ``monitor`` stats, then a histogram's ``count`` (so a rate like
  nonfinite-losses / train-steps mixes sources freely).
- **latency** (`latency_slo`): a histogram + threshold — e.g.
  ``serve.ttft_ms p99 < 50ms`` is objective 0.99 with threshold 50.0:
  at most 1% of window observations above 50ms. Good events are counted
  from the delta buckets at bucket granularity (the threshold
  effectively snaps down to its containing bucket's lower boundary).

Burn rate = (bad fraction over the window) / (1 - objective): 1.0 means
spending the error budget exactly at the rate that exhausts it at the
window's end. An alert condition requires the threshold exceeded in BOTH
a long window and its short companion (the short window gates on
*current* badness, so a long-ago burst doesn't page for hours after
recovery). `default_windows()` ships the classic fast 1h/5m page pair
(14.4x) and slow 3d/6h warn pair (1x), with a ``scale`` knob that
shrinks wall-clock for tests.

Dark by default, like everything in observability: `SloEngine.tick()`
returns immediately when `metrics.active_registry()` is None and no
explicit snapshot is passed — no ring growth, no gauges, no I/O — and
nothing here imports jax.
"""
from __future__ import annotations

import bisect
import collections
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import metrics as _metrics

_SEV_RANK = {"warn": 1, "page": 2}


class BurnWindow:
    """One (long, short) burn-rate window pair with its firing threshold."""

    __slots__ = ("long_s", "short_s", "factor", "severity")

    def __init__(self, long_s: float, short_s: float, factor: float,
                 severity: str = "page"):
        if severity not in _SEV_RANK:
            raise ValueError(f"severity must be warn|page, got {severity!r}")
        if not 0 < short_s <= long_s:
            raise ValueError("need 0 < short_s <= long_s")
        self.long_s = float(long_s)
        self.short_s = float(short_s)
        self.factor = float(factor)
        self.severity = severity

    def as_dict(self) -> dict:
        return {"long_s": self.long_s, "short_s": self.short_s,
                "factor": self.factor, "severity": self.severity}

    def __repr__(self):
        return (f"BurnWindow({self.long_s:g}s/{self.short_s:g}s "
                f"x{self.factor:g} {self.severity})")


def default_windows(scale: float = 1.0) -> Tuple[BurnWindow, ...]:
    """The SRE-workbook pairs: fast 1h/5m page at 14.4x budget burn
    (2% of a 30d budget in 1h) + slow 3d/6h warn at 1x. ``scale``
    multiplies every window (e.g. scale=1/3600 turns hours into
    seconds for tests) without changing the burn thresholds."""
    s = float(scale)
    return (BurnWindow(3600.0 * s, 300.0 * s, 14.4, "page"),
            BurnWindow(259200.0 * s, 21600.0 * s, 1.0, "warn"))


class SloSpec:
    """One declarative objective over registry-resident signals.

    Use the `ratio_slo` / `latency_slo` constructors rather than spelling
    the fields out. ``objective`` is the good-events target in (0, 1);
    the error budget is ``1 - objective``. ``labels`` tag the spec (the
    router's self-healing hook keys on ``labels["replica"]``).
    """

    def __init__(self, name: str, kind: str, objective: float,
                 windows: Optional[Sequence[BurnWindow]] = None,
                 bad: Optional[str] = None, total: Optional[str] = None,
                 metric: Optional[str] = None,
                 threshold: Optional[float] = None,
                 labels: Optional[Dict[str, str]] = None,
                 description: str = ""):
        if kind not in ("ratio", "latency"):
            raise ValueError(f"kind must be ratio|latency, got {kind!r}")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if kind == "ratio" and (not bad or not total):
            raise ValueError("ratio SLO needs bad= and total= metric names")
        if kind == "latency" and (not metric or threshold is None):
            raise ValueError("latency SLO needs metric= and threshold=")
        self.name = str(name)
        self.kind = kind
        self.objective = float(objective)
        self.windows: Tuple[BurnWindow, ...] = tuple(
            windows if windows is not None else default_windows())
        if not self.windows:
            raise ValueError("SloSpec needs at least one BurnWindow")
        self.bad = bad
        self.total = total
        self.metric = metric
        self.threshold = None if threshold is None else float(threshold)
        self.labels = dict(labels or {})
        self.description = description

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def as_dict(self) -> dict:
        out = {"name": self.name, "kind": self.kind,
               "objective": self.objective,
               "windows": [w.as_dict() for w in self.windows],
               "labels": dict(self.labels)}
        if self.kind == "ratio":
            out.update(bad=self.bad, total=self.total)
        else:
            out.update(metric=self.metric, threshold=self.threshold)
        return out

    def __repr__(self):
        sli = (f"{self.bad}/{self.total}" if self.kind == "ratio"
               else f"{self.metric}<={self.threshold:g}")
        return f"SloSpec({self.name}: {sli} @ {self.objective})"


def ratio_slo(name: str, bad: str, total: str, objective: float,
              windows: Optional[Sequence[BurnWindow]] = None,
              labels: Optional[Dict[str, str]] = None,
              description: str = "") -> SloSpec:
    """Counter-ratio SLI: ``bad/total`` events over the window must stay
    under ``1 - objective`` (e.g. serve.errors / serve.requests @ 0.999)."""
    return SloSpec(name, "ratio", objective, windows=windows, bad=bad,
                   total=total, labels=labels, description=description)


def latency_slo(name: str, metric: str, threshold: float, objective: float,
                windows: Optional[Sequence[BurnWindow]] = None,
                labels: Optional[Dict[str, str]] = None,
                description: str = "") -> SloSpec:
    """Histogram-percentile SLI: ``metric pXX <= threshold`` where
    XX = objective*100 (e.g. serve.ttft_ms p99 < 50ms is objective 0.99,
    threshold 50.0)."""
    return SloSpec(name, "latency", objective, windows=windows,
                   metric=metric, threshold=threshold, labels=labels,
                   description=description)


# ---- SLI event extraction ---------------------------------------------------

def _events(snap: dict, name: str) -> float:
    """Monotonic event count for ``name`` from a registry snapshot:
    counters first, then absorbed monitor stats, then histogram count."""
    v = snap.get("counters", {}).get(name)
    if v is not None:
        return float(v)
    rep = snap.get("monitor", {}).get(name)
    if rep is not None:
        return float(rep.get("value", 0.0))
    h = snap.get("histograms", {}).get(name)
    if h is not None:
        return float(h.get("count", 0))
    return 0.0


def _good_bad(spec: SloSpec, delta: dict) -> Tuple[float, float]:
    """(good, bad) event counts for a spec over one window-delta snapshot."""
    if spec.kind == "ratio":
        bad = _events(delta, spec.bad)
        total = _events(delta, spec.total)
        return max(0.0, total - bad), bad
    h = delta.get("histograms", {}).get(spec.metric)
    if h is None or not h.get("count"):
        return 0.0, 0.0
    boundaries = h["boundaries"]
    counts = h["counts"]
    # buckets whose upper bound <= threshold are wholly good; the bucket
    # straddling the threshold counts bad (conservative: the threshold
    # snaps down to bucket granularity, never hides a breach)
    k = bisect.bisect_right(boundaries, spec.threshold)
    good = float(sum(counts[:k]))
    return good, float(h["count"]) - good


def burn_rate(spec: SloSpec, delta: dict) -> float:
    """Error-budget burn rate over one window delta: bad-fraction divided
    by the budget. 0.0 with no traffic (an idle window spends nothing)."""
    good, bad = _good_bad(spec, delta)
    total = good + bad
    if total <= 0:
        return 0.0
    return (bad / total) / spec.budget


# ---- snapshot ring ----------------------------------------------------------

class SnapshotRing:
    """Timestamped registry snapshots; window deltas by exact subtraction.

    ``push()`` appends and trims entries older than the retention horizon
    (longest window + slack); ``delta(window_s)`` subtracts the newest
    snapshot taken at-or-before ``now - window_s`` from the latest (the
    oldest entry serves as baseline while history is still shorter than
    the window — the partial-window burn is computed over what exists,
    matching how a freshly-deployed alerting stack behaves)."""

    def __init__(self, retention_s: float, max_entries: int = 4096):
        self.retention_s = float(retention_s)
        self.max_entries = int(max_entries)
        self._entries: collections.deque = collections.deque()

    def __len__(self) -> int:
        return len(self._entries)

    def push(self, ts: float, snapshot: dict) -> None:
        self._entries.append((float(ts), snapshot))
        horizon = float(ts) - self.retention_s
        while len(self._entries) > 2 and (
                self._entries[0][0] < horizon
                or len(self._entries) > self.max_entries):
            self._entries.popleft()

    def latest(self) -> Optional[Tuple[float, dict]]:
        return self._entries[-1] if self._entries else None

    def at(self, ts: float) -> Optional[Tuple[float, dict]]:
        """Newest entry with timestamp <= ts (None before history)."""
        best = None
        for t, snap in self._entries:
            if t <= ts:
                best = (t, snap)
            else:
                break
        return best

    def delta(self, window_s: float, now: Optional[float] = None
              ) -> Optional[dict]:
        """Exact event delta over the trailing ``window_s`` (None when the
        ring is empty). The returned snapshot-shaped dict carries a
        ``_window_s`` key with the actual covered span."""
        if not self._entries:
            return None
        t1, curr = self._entries[-1]
        now = t1 if now is None else float(now)
        base = self.at(now - float(window_s))
        if base is None:
            base = self._entries[0]
        t0, prev = base
        if t0 >= t1:
            # baseline IS the latest snapshot: the window predates the
            # ring, so delta from empty (everything the registry has seen)
            prev = None
        d = _metrics.subtract_registry_snapshots(curr, prev)
        d["_window_s"] = (t1 - t0) if prev is not None else 0.0
        return d


# ---- evaluation -------------------------------------------------------------

def evaluate(spec: SloSpec, ring: SnapshotRing,
             now: Optional[float] = None) -> dict:
    """Multi-window multi-burn-rate evaluation of one spec.

    Each window pair fires when burn >= factor over BOTH its long and
    short windows; the result's severity is the highest firing pair's.
    ``burn`` reports the fast (shortest long-window) pair's long burn —
    the number an operator watches — and ``budget_remaining`` the
    fraction of error budget left over the longest window."""
    per = []
    firing_sev = 0
    for w in spec.windows:
        d_long = ring.delta(w.long_s, now)
        d_short = ring.delta(w.short_s, now)
        b_long = burn_rate(spec, d_long) if d_long else 0.0
        b_short = burn_rate(spec, d_short) if d_short else 0.0
        hit = b_long >= w.factor and b_short >= w.factor
        if hit:
            firing_sev = max(firing_sev, _SEV_RANK[w.severity])
        per.append({"window": w.as_dict(), "burn_long": b_long,
                    "burn_short": b_short, "firing": hit})
    fast = min(range(len(spec.windows)),
               key=lambda i: spec.windows[i].long_s)
    slow = max(range(len(spec.windows)),
               key=lambda i: spec.windows[i].long_s)
    d_slow = ring.delta(spec.windows[slow].long_s, now)
    if d_slow:
        good, bad = _good_bad(spec, d_slow)
        total = good + bad
        spent = (bad / total) / spec.budget if total > 0 else 0.0
    else:
        spent = 0.0
    sev = {v: k for k, v in _SEV_RANK.items()}.get(firing_sev)
    return {
        "slo": spec.name,
        "labels": dict(spec.labels),
        "burn": per[fast]["burn_long"],
        "budget_remaining": max(0.0, 1.0 - spent),
        "breach": firing_sev > 0,
        "severity": sev,
        "windows": per,
    }


# ---- alert state machine ----------------------------------------------------

class AlertManager:
    """pending -> firing -> resolved, deduped per SLO name.

    A breach opens a *pending* alert; one that persists ``for_s`` seconds
    transitions to *firing* (for_s=0: the same evaluation). While firing,
    repeated breaches only update the peak burn — no re-emission (dedup).
    A clean evaluation resolves a firing alert (emitting fire->resolve
    duration) and silently drops a pending one. Every transition becomes
    one event dict, handed to the engine's sinks and hooks; page-severity
    fires also dump the flight recorder, rate-limited per alert name
    (``dump_limit`` over the manager's lifetime, so a flapping SLO cannot
    fill the disk)."""

    def __init__(self, for_s: float = 0.0, dump_limit: int = 1):
        self.for_s = float(for_s)
        self.dump_limit = int(dump_limit)
        self.active: Dict[str, dict] = {}
        self.resolved_count = 0
        self._dumps: Dict[str, int] = {}

    def update(self, results: Sequence[dict],
               now: Optional[float] = None) -> List[dict]:
        now = time.time() if now is None else float(now)
        events: List[dict] = []
        for res in results:
            name = res["slo"]
            al = self.active.get(name)
            if res["breach"]:
                if al is None:
                    al = {"slo": name, "state": "pending", "since": now,
                          "severity": res["severity"],
                          "labels": res["labels"], "peak_burn": res["burn"]}
                    self.active[name] = al
                    events.append(self._event(al, now, res))
                al["peak_burn"] = max(al["peak_burn"], res["burn"])
                # escalation (warn pair firing, then page pair joins)
                # re-arms severity but not the state machine
                if _SEV_RANK.get(res["severity"], 0) > _SEV_RANK.get(
                        al["severity"], 0):
                    al["severity"] = res["severity"]
                if (al["state"] == "pending"
                        and now - al["since"] >= self.for_s):
                    al["state"] = "firing"
                    al["fired_at"] = now
                    events.append(self._event(al, now, res))
                    self._maybe_dump(al, res)
            elif al is not None:
                del self.active[name]
                if al["state"] == "firing":
                    al["state"] = "resolved"
                    self.resolved_count += 1
                    ev = self._event(al, now, res)
                    ev["duration_s"] = now - al["fired_at"]
                    events.append(ev)
                # pending that clears before for_s elapses: drop silently
        return events

    def firing(self, severity: Optional[str] = None) -> List[dict]:
        out = [dict(a) for a in self.active.values()
               if a["state"] == "firing"]
        if severity is not None:
            out = [a for a in out if a["severity"] == severity]
        return sorted(out, key=lambda a: a["slo"])

    def pending(self) -> List[dict]:
        return sorted((dict(a) for a in self.active.values()
                       if a["state"] == "pending"), key=lambda a: a["slo"])

    @staticmethod
    def _event(al: dict, now: float, res: dict) -> dict:
        return {"event": "alert", "ts": now, "slo": al["slo"],
                "state": al["state"], "severity": al["severity"],
                "labels": dict(al["labels"]), "burn": res["burn"],
                "peak_burn": al["peak_burn"],
                "budget_remaining": res["budget_remaining"]}

    def _maybe_dump(self, al: dict, res: dict) -> None:
        if al["severity"] != "page":
            return
        n = self._dumps.get(al["slo"], 0)
        if n >= self.dump_limit:
            return
        self._dumps[al["slo"]] = n + 1
        try:
            from . import flight_recorder as _flight
            fr = _flight.get()
            if fr is not None:
                fr.dump("slo_" + al["slo"],
                        {"alert": {k: v for k, v in al.items()},
                         "evaluation": res})
        except Exception:
            pass


# ---- default SLO packs ------------------------------------------------------

def default_serving_slos(windows: Optional[Sequence[BurnWindow]] = None,
                         replica: Optional[str] = None,
                         ttft_ms: float = 200.0, tpot_ms: float = 50.0,
                         queue_wait_ms: float = 500.0
                         ) -> List[SloSpec]:
    """The serving pack: availability (errors/requests @ 3 nines), TTFT
    and TPOT p99, queue-wait p95. With ``replica=<name>`` the specs read
    the engine's per-replica metric namespace and carry a replica label —
    the shape `ReplicaRouter.attach_slo` sheds on."""
    pfx = f"serve.replica.{replica}." if replica else "serve."
    suffix = f".{replica}" if replica else ""
    labels = {"replica": replica} if replica else None
    out = [
        ratio_slo(f"serve.availability{suffix}", pfx + "errors",
                  pfx + "requests", 0.999, windows=windows, labels=labels,
                  description="completed requests that did not error"),
        latency_slo(f"serve.ttft{suffix}", pfx + "ttft_ms", ttft_ms, 0.99,
                    windows=windows, labels=labels,
                    description=f"TTFT p99 <= {ttft_ms:g}ms"),
    ]
    if not replica:  # engine publishes tpot/queue-wait process-wide only
        out.append(latency_slo("serve.tpot", "serve.tpot_ms", tpot_ms, 0.99,
                               windows=windows,
                               description=f"TPOT p99 <= {tpot_ms:g}ms"))
        out.append(latency_slo("serve.queue_wait", "serve.queue_wait_ms",
                               queue_wait_ms, 0.95, windows=windows,
                               description="queue wait p95"))
    return out


def default_train_slos(windows: Optional[Sequence[BurnWindow]] = None,
                       step_ms: float = 5000.0) -> List[SloSpec]:
    """The training pack: step-time p99 and the nonfinite-loss rate
    (nan-loss steps / train steps, budget one per thousand)."""
    return [
        latency_slo("train.step_time", "train.step_ms", step_ms, 0.99,
                    windows=windows,
                    description=f"train step p99 <= {step_ms:g}ms"),
        ratio_slo("train.finite_loss", "engine.nan_loss_steps",
                  "train.step_ms", 0.999, windows=windows,
                  description="train steps with a finite loss"),
    ]


def default_slos(windows: Optional[Sequence[BurnWindow]] = None
                 ) -> List[SloSpec]:
    return default_serving_slos(windows) + default_train_slos(windows)


# ---- engine -----------------------------------------------------------------

class SloEngine:
    """Snapshot, evaluate, alert: one `tick()` runs the whole loop.

    Dark by default: with no active registry and no explicit snapshot,
    ``tick()`` is one None check — no ring growth, no gauges, no I/O.
    With one, each tick pushes a snapshot, evaluates every spec, writes
    ``slo.*`` gauges back (when a registry is active — fleet-offline
    evaluation over merged snapshots skips them), appends transition
    events to ``alerts_path`` / the sink, and calls the self-healing
    hooks. Thread-safe: exporter scrapes may tick concurrently with the
    owner's loop.
    """

    def __init__(self, specs: Optional[Sequence[SloSpec]] = None,
                 alerts_path: Optional[str] = None, sink=None,
                 for_s: float = 0.0, dump_limit: int = 1,
                 retention_slack: float = 1.25, max_entries: int = 4096):
        self.specs: List[SloSpec] = list(
            specs if specs is not None else default_slos())
        if not self.specs:
            raise ValueError("SloEngine needs at least one SloSpec")
        horizon = max(w.long_s for s in self.specs for w in s.windows)
        self.ring = SnapshotRing(horizon * float(retention_slack),
                                 max_entries=max_entries)
        self.alerts = AlertManager(for_s=for_s, dump_limit=dump_limit)
        self.alerts_path = alerts_path
        self.sink = sink
        self.ticks = 0
        self.events_emitted = 0
        self.last_results: List[dict] = []
        self._hooks: List[Callable[[dict], None]] = []
        self._lock = threading.Lock()

    # -- wiring
    def add_spec(self, spec: SloSpec) -> None:
        with self._lock:
            self.specs.append(spec)
            horizon = max(w.long_s for w in spec.windows)
            self.ring.retention_s = max(self.ring.retention_s,
                                        horizon * 1.25)

    def add_hook(self, fn: Callable[[dict], None]) -> None:
        """Register a transition callback (one event dict per call) — the
        self-healing attachment point (router shed, coordinator note)."""
        self._hooks.append(fn)

    # -- the loop
    def tick(self, now: Optional[float] = None,
             snapshot: Optional[dict] = None) -> List[dict]:
        """One evaluation pass; returns the transition events it caused.

        ``snapshot`` overrides the registry read — the fleet collector
        passes its merged snapshot so one process judges the whole fleet
        (and that works with no local registry at all)."""
        if snapshot is None:
            reg = _metrics.active_registry()
            if reg is None:
                return []  # dark: zero cost, zero side effects
            snapshot = reg.snapshot(include_monitor=True)
        now = time.time() if now is None else float(now)
        with self._lock:
            self.ring.push(now, snapshot)
            results = [evaluate(spec, self.ring, now) for spec in self.specs]
            self.last_results = results
            events = self.alerts.update(results, now)
            self.ticks += 1
            self.events_emitted += len(events)
        self._publish_gauges(results)
        for ev in events:
            self._emit(ev)
        return events

    def _publish_gauges(self, results: Sequence[dict]) -> None:
        reg = _metrics.active_registry()
        if reg is None:
            return
        for res in results:
            base = "slo." + res["slo"]
            reg.gauge(base + ".burn_rate").set(res["burn"])
            reg.gauge(base + ".error_budget_remaining").set(
                res["budget_remaining"])
            reg.gauge(base + ".firing").set(
                float(_SEV_RANK.get(res["severity"], 0)
                      if res["breach"] else 0))

    def _emit(self, ev: dict) -> None:
        if self.alerts_path:
            try:
                with open(self.alerts_path, "a") as f:
                    f.write(json.dumps(ev, sort_keys=True) + "\n")
            except OSError:
                pass
        if self.sink is not None:
            self.sink.write(ev)
        for fn in self._hooks:
            try:
                fn(ev)
            except Exception:
                pass  # a broken hook must not take down evaluation

    # -- views
    def firing(self, severity: Optional[str] = None) -> List[dict]:
        with self._lock:
            return self.alerts.firing(severity)

    def status(self) -> dict:
        """The /healthz + /alerts document: degraded iff a page-severity
        alert is firing."""
        with self._lock:
            firing = self.alerts.firing()
            pending = self.alerts.pending()
        degraded = any(a["severity"] == "page" for a in firing)
        return {
            "status": "degraded" if degraded else "ok",
            "firing": [{"slo": a["slo"], "severity": a["severity"],
                        "since": a.get("fired_at", a["since"]),
                        "peak_burn": a["peak_burn"],
                        "labels": a["labels"]} for a in firing],
            "pending": [a["slo"] for a in pending],
            "ticks": self.ticks,
        }

    def poll(self) -> dict:
        """tick-then-status: what a scrape-driven consumer (/healthz,
        /alerts) calls so HTTP polling IS the evaluation loop when no
        owner loop ticks — same idiom as /fleet/* collect-on-scrape."""
        self.tick()
        return self.status()

    def doc(self) -> dict:
        """Full /alerts body: status + per-spec evaluation + specs."""
        out = self.status()
        with self._lock:
            out["results"] = [dict(r) for r in self.last_results]
        out["specs"] = [s.as_dict() for s in self.specs]
        return out


# ---- process-global engine (off until installed) ----------------------------

_engine: Optional[SloEngine] = None
_glock = threading.Lock()


def install_engine(engine: Optional[SloEngine] = None, **kw) -> SloEngine:
    """Install (or build+install) the process-global SLO engine — the
    exporter's /alerts and upgraded /healthz serve it once present."""
    global _engine
    with _glock:
        _engine = engine if engine is not None else SloEngine(**kw)
        return _engine


def uninstall_engine() -> None:
    global _engine
    with _glock:
        _engine = None


def active_engine() -> Optional[SloEngine]:
    """The installed engine, else None (exporter's healthz gate: old
    plain-200 contract is preserved while this is None)."""
    return _engine

"""Pull-based metrics exporter: stdlib HTTP, Prometheus text + JSON.

A daemon-thread ``http.server`` serving the process-global
`metrics.MetricRegistry`:

    GET /metrics             Prometheus text format 0.0.4
    GET /metrics.json        full registry snapshot as JSON
    GET /fleet/metrics       merged fleet registry (Prometheus, per-worker
                             labels) when a fleet.FleetCollector is active
    GET /fleet/metrics.json  collected fleet snapshot as JSON
    GET /fleet/trace         merged cross-worker chrome-trace JSON
    GET /alerts              SLO engine state (specs, burn rates, firing
                             alerts) when a slo.SloEngine is installed
    GET /healthz             liveness probe: plain 200 "ok" until an SLO
                             engine is installed, then a JSON
                             {status, firing, ...} body that turns
                             503/degraded while a page-severity alert
                             fires (each probe ticks the engine)
    GET /capacity            autoscaling state (policy, live/retiring
                             replicas, recent scale decisions) when a
                             capacity.CapacityController is installed

Enabled via ``PADDLE_TPU_METRICS_PORT`` (the engines call
`ensure_started_from_env()` at construction — one getenv when unset, so
serving/training pay nothing unless the operator opted in). Port 0 binds
an ephemeral port; read it back from ``exporter.port`` / ``exporter.url``.
Starting the exporter also enables the metrics registry — a scrape
endpoint with nothing feeding it would be useless.

Stdlib-only; no jax import on any path here.
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import metrics as _metrics

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class _Handler(BaseHTTPRequestHandler):
    registry = None  # class attr, bound per-server subclass

    def _send(self, code, body, ctype):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        reg = self.registry or _metrics.default_registry()
        if path in ("/metrics", "/"):
            self._send(200, reg.to_prometheus(), PROM_CONTENT_TYPE)
        elif path in ("/metrics.json", "/snapshot"):
            self._send(200, json.dumps(reg.snapshot(), sort_keys=True),
                       "application/json")
        elif path.startswith("/fleet/"):
            self._do_fleet(path)
        elif path == "/healthz":
            self._do_healthz()
        elif path == "/alerts":
            self._do_alerts()
        elif path == "/capacity":
            self._do_capacity()
        else:
            self._send(404, "not found\n", "text/plain")

    def _do_healthz(self):
        from . import slo as _slo
        eng = _slo.active_engine()
        if eng is None:
            # no SLO engine installed: the original plain liveness
            # contract (200 "ok") — probes written against it keep working
            self._send(200, "ok\n", "text/plain")
            return
        try:
            st = eng.poll()  # scrape-driven evaluation, like /fleet/*
        except Exception as exc:
            self._send(503, f"slo evaluation failed: {exc}\n", "text/plain")
            return
        code = 503 if st["status"] == "degraded" else 200
        self._send(code, json.dumps(st, sort_keys=True, default=str),
                   "application/json")

    def _do_alerts(self):
        from . import slo as _slo
        eng = _slo.active_engine()
        if eng is None:
            self._send(404, "no slo engine installed\n", "text/plain")
            return
        try:
            eng.tick()
            doc = eng.doc()
        except Exception as exc:
            self._send(503, f"slo evaluation failed: {exc}\n", "text/plain")
            return
        self._send(200, json.dumps(doc, sort_keys=True, default=str),
                   "application/json")

    def _do_capacity(self):
        from . import capacity as _capacity
        ctl = _capacity.active_controller()
        if ctl is None:
            self._send(404, "no capacity controller installed\n",
                       "text/plain")
            return
        try:
            doc = ctl.doc()  # state only — scrapes must not drive scaling
        except Exception as exc:
            self._send(503, f"capacity state failed: {exc}\n", "text/plain")
            return
        self._send(200, json.dumps(doc, sort_keys=True, default=str),
                   "application/json")

    def _do_fleet(self, path):
        from . import fleet as _fleet
        coll = _fleet.active_collector()
        if coll is None:
            self._send(404, "no fleet collector installed\n", "text/plain")
            return
        try:
            fleet_snap = coll.collect()  # a scrape is a federation pass
        except Exception as exc:  # dead store mid-scrape: 503, not a crash
            self._send(503, f"fleet collect failed: {exc}\n", "text/plain")
            return
        if path == "/fleet/metrics":
            self._send(200, _fleet.fleet_to_prometheus(fleet_snap),
                       PROM_CONTENT_TYPE)
        elif path == "/fleet/metrics.json":
            self._send(200, json.dumps(fleet_snap, sort_keys=True,
                                       default=str), "application/json")
        elif path == "/fleet/trace":
            self._send(200, json.dumps(coll.merged_chrome_trace()),
                       "application/json")
        else:
            self._send(404, "not found\n", "text/plain")

    def log_message(self, fmt, *args):  # silence per-request stderr spam
        pass


class MetricsExporter:
    """HTTP scrape endpoint for a MetricRegistry (daemon thread)."""

    def __init__(self, registry: Optional[_metrics.MetricRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        self.registry = registry or _metrics.default_registry()
        self.host = host
        self.port = port
        self._server = None
        self._thread = None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            return self
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": self.registry})
        self._server = ThreadingHTTPServer((self.host, self.port), handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="paddle-tpu-metrics",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    @property
    def running(self) -> bool:
        return self._server is not None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


_global: Optional[MetricsExporter] = None
_lock = threading.Lock()


def start_exporter(port: int = 0, host: str = "127.0.0.1") -> MetricsExporter:
    """Start (or return) the process-global exporter; enables metrics."""
    global _global
    with _lock:
        if _global is None or not _global.running:
            _metrics.enable()
            _global = MetricsExporter(port=port, host=host).start()
        return _global


def get_exporter() -> Optional[MetricsExporter]:
    return _global


def stop_exporter() -> None:
    global _global
    with _lock:
        if _global is not None:
            _global.stop()
            _global = None


def ensure_started_from_env() -> Optional[MetricsExporter]:
    """Start the global exporter iff PADDLE_TPU_METRICS_PORT is set.

    Idempotent; called from engine constructors. Returns the exporter (or
    None when the env var is absent/invalid).
    """
    raw = os.environ.get("PADDLE_TPU_METRICS_PORT")
    if not raw:
        return _global
    try:
        port = int(raw)
    except ValueError:
        return _global
    with _lock:
        already = _global is not None and _global.running
    if already:
        return _global
    return start_exporter(port=port)

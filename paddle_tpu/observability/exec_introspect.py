"""Compiled-executable introspection: XLA memory/cost analysis, surfaced.

The second leg of the health tentpole (ISSUE 8): every executable the train
and serve engines build can report what it will cost BEFORE a chip runs it
— ``compiled.memory_analysis()`` (argument/output/temp/alias bytes: the
activation high-water and the donation proof) and ``cost_analysis()``
(flops, bytes accessed). This module is the one place those numbers land:

- ``capture(label, compiled)``: extract a flat stats dict, remember it
  (deduped by label), mirror it into registry gauges ``exec.<label>.<stat>``
  when metrics are active, and bump the ``exec.captured`` monitor counter.
- ``capture_jit(label, fn, args)``: AOT ``fn.lower(*args).compile()`` +
  capture — what the engines' ``introspect_executables()`` methods and the
  FLAGS_exec_introspect auto-capture hook call. The AOT path does NOT reuse
  the jit executable cache, so each capture costs one extra compile; that
  is why the flag defaults off and the dedup is by label.
- ``report_rows()``: the table ``tools/mem_report.py`` prints — the memory
  levers the ROADMAP's ZeRO item targets, measurable before it is built.

Stdlib-only at module level (observability posture); jax objects only pass
through as arguments.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_captured: Dict[str, Dict[str, Any]] = {}

_MEM_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
               "temp_size_in_bytes", "alias_size_in_bytes",
               "generated_code_size_in_bytes")
_COST_FIELDS = ("flops", "transcendentals", "bytes accessed")


def stats_for(label: str, compiled,
              extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Flat stats dict for one compiled executable. Every field is
    best-effort: backends that expose no memory_analysis (or partial cost
    models) just omit keys rather than fail. ``extra`` merges caller
    annotations (numeric ones become gauges via capture) — the engines use
    it to land analytic bounds (e.g. the fsdp live-gather window bytes)
    next to the measured temp bytes they bound."""
    out: Dict[str, Any] = {"label": label}
    if extra:
        out.update(extra)
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for f in _MEM_FIELDS:
            v = getattr(ma, f, None)
            if v is not None:
                out[f] = int(v)
        # peak HBM estimate: everything resident at once, minus what
        # donation aliases back into the arguments
        out["peak_bytes"] = (out.get("argument_size_in_bytes", 0)
                             + out.get("output_size_in_bytes", 0)
                             + out.get("temp_size_in_bytes", 0)
                             - out.get("alias_size_in_bytes", 0))
    try:
        from ..utils.hlo_inspect import cost_analysis_dict

        ca = cost_analysis_dict(compiled)
    except Exception:
        ca = {}
    for f in _COST_FIELDS:
        v = ca.get(f)
        if isinstance(v, (int, float)):
            out[f.replace(" ", "_")] = float(v)
    return out


def capture(label: str, compiled, force: bool = False,
            extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Extract + remember stats for `compiled` (deduped by label unless
    force), feed registry gauges when metrics are active."""
    with _lock:
        if not force and label in _captured:
            return _captured[label]
    st = stats_for(label, compiled, extra=extra)
    with _lock:
        _captured[label] = st
    from ..core import monitor as _monitor

    _monitor.stat("exec.captured").increase()
    from . import metrics as _metrics

    reg = _metrics.active_registry()
    if reg is not None:
        for k, v in st.items():
            if isinstance(v, (int, float)):
                reg.gauge(f"exec.{label}.{k}").set(float(v))
    return st


def capture_jit(label: str, fn, args, force: bool = False,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """AOT-lower + compile a jitted fn at the given avals and capture its
    analysis. One extra XLA compile per (new) label — diagnostic cost."""
    with _lock:
        if not force and label in _captured:
            return _captured[label]
    compiled = fn.lower(*args).compile()
    return capture(label, compiled, force=True, extra=extra)


def captured() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return dict(_captured)


def reset() -> None:
    """Drop all captured stats (test isolation)."""
    with _lock:
        _captured.clear()


def report_rows() -> List[List[Any]]:
    """[label, flops, argument, output, temp, alias, peak] rows sorted by
    label — the shape tools/mem_report.py tabulates."""
    rows = []
    for label, st in sorted(captured().items()):
        rows.append([
            label,
            st.get("flops"),
            st.get("argument_size_in_bytes"),
            st.get("output_size_in_bytes"),
            st.get("temp_size_in_bytes"),
            st.get("alias_size_in_bytes"),
            st.get("peak_bytes"),
        ])
    return rows

"""Fleet observability: cross-process metrics federation + trace context.

PRs 12-13 turned this system into a fleet (elastic worker pools over the
store, a ReplicaRouter over K serving engines) whose observability was
still strictly per-process. This module federates it:

- **Metrics federation.** Each worker runs a :class:`FleetPublisher` that
  periodically writes a compact (zlib+base64 JSON) registry snapshot +
  tracer span tail under a generation-scoped store key::

      __fleet__/gen<g>/snap/<wid>    {"wid", "ts", "deadline", "pid",
                                      "origin_unix", "snapshot", "spans"}

  reusing membership.py's lease idiom (wall-clock deadlines — records are
  compared across processes; `gc_generation` sweeps retired generations).
  The driver runs a :class:`FleetCollector` that reads every unexpired
  snapshot, evicts stale publishers past their deadline, and merges the
  registries losslessly: counters/gauges sum, log-bucket histograms merge
  elementwise (`Histogram.merge` semantics) with p50/p90/p99 recomputed
  from the merged buckets — so the fleet-wide p99 is exactly what one
  histogram observing the pooled samples would estimate. The existing
  exporter serves the result at ``/fleet/metrics`` (Prometheus, merged
  series + per-worker-labeled quantiles) and ``/fleet/metrics.json``.

- **Distributed trace context.** :class:`TraceContext` carries a request
  id + parent span id from the ReplicaRouter's placement span into the
  chosen engine's queue-wait/prefill/decode spans, so one chrome trace
  renders the routing decision and the replica execution on a single
  timeline; ``FleetCollector.merged_chrome_trace()`` stitches every
  worker's span tail onto one wall-clock-aligned timeline (per-worker
  pid rows).

Cost model matches the rest of observability: everything here is dark by
default. ``FleetPublisher.publish_once`` gates on ``active_registry()``
(no registry -> no snapshot, no store write) and nothing in this module
runs unless explicitly constructed. Payloads are bounded
(``PADDLE_TPU_FLEET_MAX_BYTES``): an oversized publish first drops its
span tail, then drops entirely and counts ``fleet.publish_drops`` so
store pressure is visible.

Env knobs (all optional): ``PADDLE_TPU_FLEET_PUBLISH_S`` (publish period,
default 2.0), ``PADDLE_TPU_FLEET_DEADLINE_S`` (staleness deadline,
default 3x period), ``PADDLE_TPU_FLEET_MAX_BYTES`` (payload bound,
default 262144), ``PADDLE_TPU_FLEET_SPAN_TAIL`` (span-tail length,
default 256).

Stdlib-only; no jax import on any path here, and no import of
distributed/ (membership imports observability — the generation counter
key is re-read here instead).
"""
from __future__ import annotations

import base64
import itertools
import json
import os
import threading
import time
import weakref
import zlib
from typing import Dict, List, Optional, Sequence

from . import metrics as _metrics
from . import tracer as _tracer

# Generation counter key — membership.py's GEN_KEY, re-declared (not
# imported: distributed/membership imports observability).
GEN_KEY = "__elastic__/gen"
FLEET_PREFIX = "__fleet__"

_DEF_PUBLISH_S = 2.0
_DEF_MAX_BYTES = 262144
_DEF_SPAN_TAIL = 256


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def current_generation(store) -> int:
    """The fleet's generation number; 0 before any coordinator ran."""
    try:
        return int(store.get(GEN_KEY, wait=False))
    except KeyError:
        return 0


def snap_key(generation: int, wid: str) -> str:
    return f"{FLEET_PREFIX}/gen{int(generation)}/snap/{wid}"


def _encode(doc: dict) -> bytes:
    """Compact store-safe payload: minified JSON -> zlib -> base64."""
    raw = json.dumps(doc, separators=(",", ":"), default=str).encode()
    return base64.b64encode(zlib.compress(raw, 6))


def _decode(blob: bytes) -> dict:
    return json.loads(zlib.decompress(base64.b64decode(blob)).decode())


# ---- trace context ----------------------------------------------------------

_req_ids = itertools.count(1)


def new_request_id() -> str:
    """Fleet-unique request id (pid-qualified so ids from different
    router/worker processes never collide in a merged trace)."""
    return f"{os.getpid():x}.{next(_req_ids)}"


class TraceContext:
    """Request-scoped trace identity carried across component boundaries.

    ``request_id`` tags every span of one request end to end;
    ``parent_span`` is the minting span's ``tracer.new_span_id()`` (the
    router's placement span), recorded on engine-side child spans so a
    chrome-trace consumer can reconstruct the parentage.
    """

    __slots__ = ("request_id", "parent_span")

    def __init__(self, request_id: Optional[str] = None,
                 parent_span: Optional[int] = None):
        self.request_id = (request_id if request_id is not None
                           else new_request_id())
        self.parent_span = parent_span

    def span_args(self) -> dict:
        out = {"request_id": self.request_id}
        if self.parent_span is not None:
            out["parent_span"] = self.parent_span
        return out

    def __repr__(self):
        return (f"TraceContext(request_id={self.request_id!r}, "
                f"parent_span={self.parent_span!r})")


# ---- registry-snapshot federation -------------------------------------------

def merge_registry_snapshots(snaps: Sequence[Optional[dict]]) -> dict:
    """Merge per-worker ``MetricRegistry.snapshot()`` dicts into one
    fleet-wide snapshot: counters and gauges sum, monitor stats sum value /
    max peak, histograms merge losslessly via
    :func:`metrics.merge_histogram_snapshots` (merged count == sum of
    per-worker counts; percentiles recomputed from merged buckets)."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {},
                 "monitor": {}}
    hists: Dict[str, List[dict]] = {}
    for s in snaps:
        if not s:
            continue
        for name, v in s.get("counters", {}).items():
            out["counters"][name] = out["counters"].get(name, 0.0) + v
        for name, v in s.get("gauges", {}).items():
            out["gauges"][name] = out["gauges"].get(name, 0.0) + v
        for name, h in s.get("histograms", {}).items():
            hists.setdefault(name, []).append(h)
        for name, rep in s.get("monitor", {}).items():
            m = out["monitor"].setdefault(name, {"value": 0.0, "peak": 0.0})
            m["value"] += float(rep.get("value", 0.0))
            m["peak"] = max(m["peak"], float(rep.get("peak", 0.0)))
    for name, hs in sorted(hists.items()):
        merged = _metrics.merge_histogram_snapshots(hs)
        if merged is not None:
            out["histograms"][name] = merged
    return out


def compact_snapshot(snap: dict) -> dict:
    """Per-bucket arrays -> summary stats (count/sum/min/max/p50/p90/p99),
    the right shape for flight dumps and bench rows."""
    out = dict(snap)
    out["histograms"] = {
        name: {k: v for k, v in h.items()
               if k not in ("boundaries", "counts", "kind")}
        for name, h in snap.get("histograms", {}).items()}
    return out


# ---- publisher --------------------------------------------------------------

class FleetPublisher:
    """One worker's metrics/span feed into the fleet store namespace.

    ``publish_once()`` snapshots the active registry (dark: returns False
    without touching the store when metrics are off), bounds the payload,
    and writes it under the *current* generation — after a reformation the
    next publish lands in the new namespace automatically, and
    ``gc_generation`` sweeps the old one. ``start()`` runs it on a daemon
    thread every ``interval_s``.
    """

    def __init__(self, store, worker_id: str,
                 interval_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 max_bytes: Optional[int] = None,
                 span_tail: Optional[int] = None):
        self.store = store
        self.worker_id = str(worker_id)
        self.interval_s = float(
            interval_s if interval_s is not None
            else _env_float("PADDLE_TPU_FLEET_PUBLISH_S", _DEF_PUBLISH_S))
        self.deadline_s = float(
            deadline_s if deadline_s is not None
            else _env_float("PADDLE_TPU_FLEET_DEADLINE_S",
                            3.0 * self.interval_s))
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else _env_int("PADDLE_TPU_FLEET_MAX_BYTES", _DEF_MAX_BYTES))
        self.span_tail = int(
            span_tail if span_tail is not None
            else _env_int("PADDLE_TPU_FLEET_SPAN_TAIL", _DEF_SPAN_TAIL))
        self.publishes = 0
        self.drops = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _span_tail(self) -> List[dict]:
        tr = _tracer.get_tracer()
        if not tr.enabled or self.span_tail <= 0:
            return []
        return tr.events()[-self.span_tail:]

    def payload(self) -> Optional[bytes]:
        """Encoded snapshot document, or None when dark / oversized."""
        reg = _metrics.active_registry()
        if reg is None:
            return None
        now = time.time()
        doc = {
            "wid": self.worker_id,
            "pid": os.getpid(),
            "ts": now,
            "deadline": now + self.deadline_s,
            # maps tracer perf_counter-relative span ts to wall clock so
            # the collector can align workers on one merged timeline
            "origin_unix": now - (time.perf_counter() - _tracer._ORIGIN),
            "snapshot": reg.snapshot(include_monitor=True),
            "spans": self._span_tail(),
        }
        blob = _encode(doc)
        if len(blob) > self.max_bytes and doc["spans"]:
            doc["spans"] = []  # spans are the elastic part; shed them first
            blob = _encode(doc)
        if len(blob) > self.max_bytes:
            reg.counter("fleet.publish_drops").inc()
            self.drops += 1
            return None
        return blob

    def publish_once(self) -> bool:
        blob = self.payload()
        if blob is None:
            return False
        gen = current_generation(self.store)
        self.store.set(snap_key(gen, self.worker_id), blob)
        self.publishes += 1
        reg = _metrics.active_registry()
        if reg is not None:
            reg.counter("fleet.publishes").inc()
        return True

    # ---- background loop ----
    def start(self) -> "FleetPublisher":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.publish_once()
                except Exception:
                    return  # dead store: the deadline evicts us naturally

        self._thread = threading.Thread(
            target=_loop, name=f"fleet-pub-{self.worker_id}", daemon=True)
        self._thread.start()
        return self

    def stop(self, final_publish: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if final_publish:
            try:
                self.publish_once()
            except Exception:
                pass

    def retire(self) -> None:
        """Gracefully remove this worker's snapshot (announce-leave
        analogue: the collector sees a departure, not a deadline expiry)."""
        self.stop()
        try:
            gen = current_generation(self.store)
            self.store.delete_key(snap_key(gen, self.worker_id))
        except Exception:
            pass


# ---- collector --------------------------------------------------------------

class FleetCollector:
    """Driver-side federation point: read every worker's snapshot under
    the current generation, evict the stale (deadline passed — the read IS
    the failure detector, like ``live_members``), merge the rest."""

    def __init__(self, store, span_limit: int = 20000):
        self.store = store
        self.span_limit = int(span_limit)
        self.collections = 0
        self.evictions = 0
        self.last: Optional[dict] = None
        self._docs: Dict[str, dict] = {}
        self._slo = None  # optional slo.SloEngine judging merged snapshots
        self._lock = threading.Lock()

    def attach_slo(self, slo_engine) -> None:
        """Evaluate fleet-level SLOs on every collect(): the engine's ring
        is fed the *merged* snapshot, so burn rates and alerts reflect the
        whole fleet (works with no local registry — merged counts are the
        evaluation input, gauges are skipped when metrics are dark). The
        collected document gains a ``slo`` section."""
        self._slo = slo_engine

    def generation(self) -> int:
        return current_generation(self.store)

    def _read_docs(self, generation: int):
        prefix = f"{FLEET_PREFIX}/gen{int(generation)}/snap/"
        now = time.time()
        docs: Dict[str, dict] = {}
        evicted: List[str] = []
        for key in self.store.list_keys(prefix):
            try:
                doc = _decode(self.store.get(key, wait=False))
            except KeyError:
                continue
            except Exception:
                doc = None  # corrupt payload: evict like a stale one
            wid = (doc or {}).get("wid") or key[len(prefix):]
            if doc is None or float(doc.get("deadline", 0.0)) < now:
                self.store.delete_key(key)
                evicted.append(wid)
                continue
            docs[wid] = doc
        return docs, evicted

    def collect(self) -> dict:
        """One federation pass. Returns (and caches as ``.last``) the
        fleet snapshot: merged registry + per-worker registries + ages."""
        t0 = time.perf_counter()
        gen = self.generation()
        docs, evicted = self._read_docs(gen)
        now = time.time()
        merged = merge_registry_snapshots(
            [d.get("snapshot") for d in docs.values()])
        result = {
            "generation": gen,
            "ts": now,
            "workers": {wid: {"ts": d.get("ts"), "pid": d.get("pid"),
                              "age_s": max(0.0, now - float(d.get("ts", now)))}
                        for wid, d in sorted(docs.items())},
            "evicted": evicted,
            "merged": merged,
            "per_worker": {wid: d.get("snapshot") or {}
                           for wid, d in sorted(docs.items())},
        }
        if self._slo is not None:
            try:
                events = self._slo.tick(now=now, snapshot=merged)
                result["slo"] = self._slo.status()
                if events:
                    result["slo"]["events"] = events
            except Exception as exc:  # judgement must not break federation
                result["slo"] = {"status": "error", "error": repr(exc)}
        with self._lock:
            self.last = result
            self._docs = docs
        self.collections += 1
        self.evictions += len(evicted)
        reg = _metrics.active_registry()
        if reg is not None:
            reg.counter("fleet.collections").inc()
            if evicted:
                reg.counter("fleet.evicted").inc(len(evicted))
            reg.gauge("fleet.workers").set(float(len(docs)))
            reg.histogram("fleet.collect_ms").observe(
                (time.perf_counter() - t0) * 1000.0)
            for w in result["workers"].values():
                reg.histogram("fleet.snapshot_age_ms").observe(
                    w["age_s"] * 1000.0)
        return result

    # ---- merged views ----
    def merged_chrome_trace(self) -> dict:
        """Every worker's span tail on one wall-clock-aligned chrome-trace
        timeline: one pid row per worker (process_name ``fleet:<wid>``),
        span ts shifted by each publisher's ``origin_unix`` so concurrent
        work lines up across processes."""
        with self._lock:
            docs = dict(self._docs)
        trace_events: List[dict] = []
        origins = [float(d.get("origin_unix", 0.0)) for d in docs.values()
                   if d.get("spans")]
        base = min(origins) if origins else 0.0
        emitted = 0
        for i, (wid, doc) in enumerate(sorted(docs.items())):
            pid = int(doc.get("pid") or (i + 1))
            trace_events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"fleet:{wid}"},
            })
            shift = float(doc.get("origin_unix", base)) - base
            for ev in doc.get("spans") or []:
                if emitted >= self.span_limit:
                    break
                out = {"name": ev.get("name"), "pid": pid,
                       "tid": ev.get("tid", 0),
                       "ts": round((float(ev.get("ts", 0.0)) + shift) * 1e6,
                                   3)}
                dur = ev.get("dur")
                if dur is None:
                    out["ph"] = "i"
                    out["s"] = "t"
                else:
                    out["ph"] = "X"
                    out["dur"] = round(float(dur) * 1e6, 3)
                if ev.get("args"):
                    out["args"] = dict(ev["args"])
                trace_events.append(out)
                emitted += 1
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}

    def to_json(self) -> str:
        fleet = self.last if self.last is not None else self.collect()
        return json.dumps(fleet, sort_keys=True, default=str)

    def to_prometheus(self) -> str:
        return fleet_to_prometheus(
            self.last if self.last is not None else self.collect())


def fleet_to_prometheus(fleet: dict, namespace: str = "paddle_tpu_fleet"
                        ) -> str:
    """Prometheus text 0.0.4 for a collected fleet snapshot: merged
    counters/gauges/histograms (cumulative buckets + recomputed quantile
    gauges), plus per-worker-labeled quantiles and counts alongside."""
    san = _metrics._sanitize
    lines: List[str] = []
    ns = san(namespace)
    merged = fleet.get("merged") or {}

    def emit(name, kind, help_, series):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(series)

    lines.append(f"# HELP {ns}_workers live publishers in the fleet")
    lines.append(f"# TYPE {ns}_workers gauge")
    lines.append(f"{ns}_workers {len(fleet.get('workers') or {})}")
    lines.append(f"{ns}_generation {fleet.get('generation', 0)}")
    for name, v in sorted((merged.get("counters") or {}).items()):
        full = f"{ns}_{san(name)}_total"
        emit(full, "counter", f"fleet-merged {name}",
             [f"{full} {_metrics._fmt_val(v)}"])
    for name, v in sorted((merged.get("gauges") or {}).items()):
        full = f"{ns}_{san(name)}"
        emit(full, "gauge", f"fleet-merged {name}",
             [f"{full} {_metrics._fmt_val(v)}"])
    per_worker = fleet.get("per_worker") or {}
    for name, snap in sorted((merged.get("histograms") or {}).items()):
        full = f"{ns}_{san(name)}"
        series, cum = [], 0
        for b, c in zip(snap["boundaries"], snap["counts"]):
            cum += c
            series.append(f'{full}_bucket{{le="{_metrics._fmt_le(b)}"}} {cum}')
        cum += snap["counts"][-1]
        series.append(f'{full}_bucket{{le="+Inf"}} {cum}')
        series.append(f"{full}_sum {_metrics._fmt_val(snap['sum'])}")
        series.append(f"{full}_count {snap['count']}")
        for q in ("p50", "p90", "p99"):
            if snap.get(q) is not None:
                series.append(f"{full}_{q} {_metrics._fmt_val(snap[q])}")
        # per-worker quantiles next to the merged series, label-scoped
        for wid, wsnap in sorted(per_worker.items()):
            h = (wsnap.get("histograms") or {}).get(name)
            if not h or not h.get("count"):
                continue
            series.append(f'{full}_count{{worker="{wid}"}} {h["count"]}')
            for q in ("p50", "p90", "p99"):
                if h.get(q) is not None:
                    series.append(
                        f'{full}_{q}{{worker="{wid}"}} '
                        f'{_metrics._fmt_val(h[q])}')
        emit(full, "histogram", f"fleet-merged {name}", series)
    return "\n".join(lines) + "\n"


# ---- process-global wiring (exporter routes, flight dumps) ------------------

_collector: Optional[FleetCollector] = None
_router_ref = None  # weakref.ref to the last-registered ReplicaRouter
_state_lock = threading.Lock()


def install_collector(collector: FleetCollector) -> FleetCollector:
    """Make a collector visible to the exporter's ``/fleet/metrics``
    routes and the flight recorder's crash-dump context."""
    global _collector
    with _state_lock:
        _collector = collector
    return collector


def uninstall_collector() -> None:
    global _collector
    with _state_lock:
        _collector = None


def active_collector() -> Optional[FleetCollector]:
    return _collector


def register_router(router) -> None:
    """Remember the live ReplicaRouter (weakly) so flight dumps can embed
    its recent placement decisions."""
    global _router_ref
    with _state_lock:
        _router_ref = weakref.ref(router)


def flight_context() -> Optional[dict]:
    """Fleet-level context for a crash dump: the last collected fleet
    snapshot (compact) + the router's placement tail. None when neither a
    collector nor a router is live — the dump stays per-process then."""
    out = {}
    c = _collector
    if c is not None and c.last is not None:
        last = c.last
        out["fleet"] = {
            "generation": last.get("generation"),
            "ts": last.get("ts"),
            "workers": last.get("workers"),
            "evicted": last.get("evicted"),
            "merged": compact_snapshot(last.get("merged") or {}),
        }
    ref = _router_ref
    router = ref() if ref is not None else None
    if router is not None:
        try:
            out["router_placements"] = router.recent_placements()
        except Exception:
            pass
    return out or None

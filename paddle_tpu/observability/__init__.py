"""Unified runtime telemetry (ISSUE 1 tentpole).

Three layers, one subsystem:

- ``tracer``: thread-safe host span recorder -> chrome-trace JSON that
  interleaves with the jax.profiler device timeline. profiler.RecordEvent
  feeds it, so existing markers show up with zero caller changes.
- compile/dispatch counters: core.dispatch and distributed.engine register
  dispatch counts, rule-cache hit/miss, nan/inf hits, jit compile count and
  wall time in ``core.monitor.registry()``.
- ``StepTelemetry``: per-train-step JSONL records (wall time, tokens/s,
  TFLOP/s, MFU, memory high-water, compile counters) with pluggable sinks;
  wired into distributed.engine.TrainStepEngine and the hapi fit loop.

Everything is off-by-default and stdlib-only at import time: enabling costs
one env var (PADDLE_TPU_TELEMETRY_DIR) or one method call
(engine.enable_telemetry()); disabled, no jax import, no I/O, no spans.
"""
from .flops import (  # noqa: F401
    PEAK_TFLOPS, peak_flops_per_sec, transformer_flops_per_token,
)
from .step_telemetry import (  # noqa: F401
    InMemorySink, JsonlSink, StepTelemetry,
)
from .tracer import (  # noqa: F401
    Tracer, enabled, get_tracer, span,
)

__all__ = [
    "Tracer", "get_tracer", "span", "enabled",
    "StepTelemetry", "JsonlSink", "InMemorySink",
    "transformer_flops_per_token", "peak_flops_per_sec", "PEAK_TFLOPS",
]

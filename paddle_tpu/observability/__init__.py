"""Unified runtime telemetry (ISSUE 1 tentpole, extended by ISSUE 7).

Layers, one subsystem:

- ``tracer``: thread-safe host span recorder -> chrome-trace JSON that
  interleaves with the jax.profiler device timeline. profiler.RecordEvent
  feeds it, so existing markers show up with zero caller changes.
- compile/dispatch counters: core.dispatch and distributed.engine register
  dispatch counts, rule-cache hit/miss, nan/inf hits, jit compile count and
  wall time in ``core.monitor.registry()``.
- ``StepTelemetry``: per-train-step JSONL records (wall time, tokens/s,
  TFLOP/s, MFU, memory high-water, compile counters) with pluggable sinks;
  wired into distributed.engine.TrainStepEngine and the hapi fit loop.
- ``metrics``: typed registry (counters/gauges/log-bucket histograms with
  p50/p90/p99) absorbing the monitor counters into one snapshot.
- ``exporter``: stdlib-HTTP pull endpoint (Prometheus text + JSON),
  enabled via PADDLE_TPU_METRICS_PORT.
- ``flight_recorder``: bounded ring of recent step/serve records dumped to
  disk on NaN/exception/explicit trigger (PADDLE_TPU_FLIGHT_DIR).
- ``fleet``: cross-process federation — per-worker registry snapshots
  published into generation-scoped store keys, a collector merging
  log-bucket histograms losslessly (``/fleet/metrics``), and
  ``TraceContext`` carrying request id + parent span across the
  router -> engine boundary (PADDLE_TPU_FLEET_*).
- ``capacity``: closed-loop SLO-driven autoscaling — a CapacityController
  polling firing burn-rate alerts + occupancy/queue gauges into a target
  replica count, acting through the router's spawn/drain machinery, every
  decision a traced span + capacity.jsonl record (``/capacity`` route).
- ``health``: in-program training-health stats (grad/weight/update norms,
  non-finite localization by parameter name) riding the compiled step as
  ONE packed aux output, fetched every FLAGS_health_interval steps
  (FLAGS_health_monitor / PADDLE_TPU_HEALTH_DIR).
- ``exec_introspect``: XLA memory_analysis()/cost_analysis() capture for
  every train/serve executable (FLAGS_exec_introspect, registry gauges
  exec.<label>.*, tools/mem_report.py).

Everything is off-by-default and stdlib-only at import time: enabling costs
one env var (PADDLE_TPU_TELEMETRY_DIR / PADDLE_TPU_METRICS_PORT /
PADDLE_TPU_FLIGHT_DIR) or one method call; disabled, no jax import, no I/O,
no spans, no per-step work beyond a None check.
"""
from . import capacity, exec_introspect, exporter, fleet, flight_recorder, health, metrics, slo  # noqa: F401,E501
from .capacity import (  # noqa: F401
    CapacityController, CapacityPolicy, active_controller,
    install_controller, uninstall_controller,
)
from .exporter import (  # noqa: F401
    MetricsExporter, ensure_started_from_env, get_exporter, start_exporter,
    stop_exporter,
)
from .fleet import (  # noqa: F401
    FleetCollector, FleetPublisher, TraceContext, active_collector,
    fleet_to_prometheus, install_collector, merge_registry_snapshots,
    register_router, uninstall_collector,
)
from .flight_recorder import FlightRecorder  # noqa: F401
from .health import TrainingHealthMonitor, segment_layout  # noqa: F401
from .flops import (  # noqa: F401
    PEAK_TFLOPS, peak_flops_per_sec, transformer_flops_per_token,
)
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, active_registry,
    default_registry, estimate_percentile, log_buckets,
    merge_histogram_snapshots, subtract_histogram_snapshots,
    subtract_registry_snapshots,
)
from .slo import (  # noqa: F401
    AlertManager, BurnWindow, SloEngine, SloSpec, SnapshotRing,
    active_engine, default_serving_slos, default_slos, default_train_slos,
    default_windows, install_engine, latency_slo, ratio_slo,
    uninstall_engine,
)
from .step_telemetry import (  # noqa: F401
    InMemorySink, JsonlSink, StepTelemetry,
)
from .tracer import (  # noqa: F401
    Tracer, enabled, get_tracer, span,
)

__all__ = [
    "Tracer", "get_tracer", "span", "enabled",
    "CapacityController", "CapacityPolicy", "capacity",
    "install_controller", "uninstall_controller", "active_controller",
    "StepTelemetry", "JsonlSink", "InMemorySink",
    "transformer_flops_per_token", "peak_flops_per_sec", "PEAK_TFLOPS",
    "Counter", "Gauge", "Histogram", "MetricRegistry",
    "default_registry", "active_registry", "estimate_percentile",
    "log_buckets", "merge_histogram_snapshots",
    "subtract_histogram_snapshots", "subtract_registry_snapshots",
    "SloSpec", "SloEngine", "SnapshotRing", "AlertManager", "BurnWindow",
    "ratio_slo", "latency_slo", "default_windows", "default_slos",
    "default_serving_slos", "default_train_slos", "install_engine",
    "uninstall_engine", "active_engine", "slo",
    "FleetCollector", "FleetPublisher", "TraceContext", "fleet",
    "install_collector", "uninstall_collector", "active_collector",
    "register_router", "merge_registry_snapshots", "fleet_to_prometheus",
    "MetricsExporter", "start_exporter", "stop_exporter", "get_exporter",
    "ensure_started_from_env",
    "FlightRecorder", "metrics", "exporter", "flight_recorder",
    "TrainingHealthMonitor", "segment_layout", "health", "exec_introspect",
]

"""Typed metric registry: counters, gauges, log-bucketed histograms.

The production-metrics half of paddle_tpu/observability (PR 1 added spans +
per-step JSONL; this adds *distributions* and a scrapeable registry):

- ``Counter`` / ``Gauge``: thread-safe scalars.
- ``Histogram``: fixed-boundary buckets (log-spaced by default) with exact
  ``min/max/sum/count`` and interpolated p50/p90/p99 estimation — the same
  shape Prometheus client libraries expose, so `observability/exporter.py`
  can render the text format directly from a snapshot.
- ``MetricRegistry``: name -> metric, get-or-create, one lock per metric.
  ``snapshot()`` additionally absorbs the raw monotonic counters living in
  `core.monitor` (jit_compiles, nan_inf_hits, serving.*, grad_comm.* ...),
  so one scrape sees both worlds without double instrumentation.

Everything here is stdlib-only and importable without jax (the disabled
path of the engines never pays an import); see
tests/test_profiler.py::test_observability_is_stdlib_without_jax.

Off by default: `active_registry()` returns None until `enable()` (called
by the exporter's env-var autostart or a test). Engine hot paths gate all
observations on that single None check.
"""
from __future__ import annotations

import bisect
import json
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple


def log_buckets(lo: float, hi: float, factor: float = 2.0) -> Tuple[float, ...]:
    """Geometric bucket upper bounds covering [lo, hi]: lo, lo*f, ... >= hi."""
    if lo <= 0 or hi <= lo or factor <= 1:
        raise ValueError("need 0 < lo < hi and factor > 1")
    out = [lo]
    while out[-1] < hi:
        out.append(out[-1] * factor)
    return tuple(out)


# Default boundaries for millisecond-valued latency histograms: 0.1ms .. ~3.4min
DEFAULT_MS_BUCKETS = log_buckets(0.1, 200_000.0, 2.0)


class Counter:
    """Monotonic float counter."""

    kind = "counter"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def merge(self, other: "Counter") -> None:
        """Absorb another counter's value (fleet federation: merged total
        equals the sum of the per-worker totals)."""
        n = other.value
        with self._lock:
            self._value += n

    def snapshot(self) -> dict:
        return {"kind": self.kind, "value": self.value}


class Gauge:
    """Last-write-wins scalar (queue depth, occupancy, ...)."""

    kind = "gauge"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def snapshot(self) -> dict:
        return {"kind": self.kind, "value": self.value}


class Histogram:
    """Fixed-boundary histogram with exact moments and estimated percentiles.

    ``boundaries`` are bucket *upper* bounds (like Prometheus ``le``); an
    implicit +Inf bucket catches overflow. Percentiles are estimated by
    linear interpolation inside the bucket holding the target rank, then
    clamped to the exactly-tracked [min, max] — so the estimate is never
    off by more than one bucket width.
    """

    kind = "histogram"

    def __init__(self, name: str, boundaries: Sequence[float] = None,
                 description: str = ""):
        self.name = name
        self.description = description
        bs = tuple(boundaries) if boundaries is not None else DEFAULT_MS_BUCKETS
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError("boundaries must be strictly increasing")
        self.boundaries: Tuple[float, ...] = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # last = +Inf overflow
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.boundaries, v)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def merge(self, other: "Histogram") -> None:
        """Absorb another histogram with identical boundaries, losslessly.

        Bucket counts and the exact moments (count/sum/min/max) add
        elementwise — exactly what one histogram observing the pooled
        samples would hold — so percentile estimates recomputed from the
        merged buckets stay within one bucket width of the pooled truth.
        """
        if tuple(other.boundaries) != self.boundaries:
            raise ValueError(
                f"histogram {self.name!r}: cannot merge boundaries "
                f"{list(other.boundaries)} into {list(self.boundaries)}")
        with other._lock:
            counts = list(other._counts)
            count, total = other._count, other._sum
            mn, mx = other._min, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += count
            self._sum += total
            if mn < self._min:
                self._min = mn
            if mx > self._max:
                self._max = mx

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            count, total = self._count, self._sum
            mn = self._min if self._count else None
            mx = self._max if self._count else None
        snap = {
            "kind": self.kind,
            "boundaries": list(self.boundaries),
            "counts": counts,
            "count": count,
            "sum": total,
            "min": mn,
            "max": mx,
        }
        for q in (0.5, 0.9, 0.99):
            snap["p%g" % (q * 100)] = estimate_percentile(snap, q)
        return snap

    def percentile(self, q: float) -> Optional[float]:
        return estimate_percentile(self.snapshot(), q)


def estimate_percentile(snap: dict, q: float) -> Optional[float]:
    """Interpolated percentile from a histogram snapshot dict.

    Works on any dict with boundaries/counts/count/min/max — usable offline
    (tools/trace_summary.py) on a JSON snapshot without a live registry.
    """
    if not 0 <= q <= 1:
        raise ValueError("q in [0, 1]")
    count = snap.get("count", 0)
    if not count:
        return None
    boundaries = snap["boundaries"]
    counts = snap["counts"]
    mn, mx = snap["min"], snap["max"]
    rank = q * count
    cum = 0.0
    for i, c in enumerate(counts):
        if cum + c >= rank and c > 0:
            # interpolate within bucket i between its lower/upper bounds
            lo = boundaries[i - 1] if i > 0 else mn
            hi = boundaries[i] if i < len(boundaries) else mx
            frac = (rank - cum) / c
            est = lo + (hi - lo) * frac
            return float(min(max(est, mn), mx))
        cum += c
    return float(mx)


def merge_histogram_snapshots(snaps: Sequence[Optional[dict]]
                              ) -> Optional[dict]:
    """Lossless merge of histogram snapshot dicts sharing one boundary set.

    Bucket counts, ``count`` and ``sum`` add elementwise; ``min``/``max``
    combine (None-aware for empty inputs); p50/p90/p99 are recomputed from
    the merged buckets — the same estimate a single histogram observing
    the pooled samples would report, so merged percentiles sit within one
    bucket width of the pooled recompute. Usable offline (fleet collector,
    tools/trace_summary.py) on JSON snapshots without a live registry.
    Returns None when no snapshot is present at all.
    """
    merged: Optional[dict] = None
    for snap in snaps:
        if snap is None:
            continue
        if merged is None:
            merged = {
                "kind": "histogram",
                "boundaries": list(snap["boundaries"]),
                "counts": list(snap["counts"]),
                "count": int(snap["count"]),
                "sum": float(snap["sum"]),
                "min": snap["min"],
                "max": snap["max"],
            }
            continue
        if list(snap["boundaries"]) != merged["boundaries"]:
            raise ValueError(
                "cannot merge histogram snapshots with different boundaries")
        merged["counts"] = [a + b for a, b in
                            zip(merged["counts"], snap["counts"])]
        merged["count"] += int(snap["count"])
        merged["sum"] += float(snap["sum"])
        mns = [v for v in (merged["min"], snap["min"]) if v is not None]
        mxs = [v for v in (merged["max"], snap["max"]) if v is not None]
        merged["min"] = min(mns) if mns else None
        merged["max"] = max(mxs) if mxs else None
    if merged is not None:
        for q in (0.5, 0.9, 0.99):
            merged["p%g" % (q * 100)] = estimate_percentile(merged, q)
    return merged


def subtract_histogram_snapshots(curr: Optional[dict], prev: Optional[dict]
                                 ) -> Optional[dict]:
    """Exact window delta of two histogram snapshots of ONE histogram.

    The dual of :func:`merge_histogram_snapshots`: given a later (``curr``)
    and an earlier (``prev``) snapshot of the same monotonically-observing
    histogram, returns the snapshot the histogram would hold had it only
    observed the samples between the two — bucket counts, ``count`` and
    ``sum`` subtract exactly (boundary mismatch raises, and so does a
    bucket going backwards: that means ``prev`` is not an earlier view of
    ``curr``). The window ``min``/``max`` are not recoverable from
    cumulative state, so they are re-derived from the delta buckets
    (first/last non-empty bucket bounds, tightened by the lifetime
    min/max) — which keeps p50/p90/p99 recomputed from the delta within
    one bucket width of a pooled recompute over the window's samples, the
    same guarantee the merge direction gives. This is the primitive the
    SLO snapshot ring uses for sliding-window percentiles; ``prev=None``
    treats the window as starting from empty.
    """
    if curr is None:
        return None
    if prev is None:
        prev = {"boundaries": curr["boundaries"],
                "counts": [0] * len(curr["counts"]),
                "count": 0, "sum": 0.0, "min": None, "max": None}
    if list(curr["boundaries"]) != list(prev["boundaries"]):
        raise ValueError(
            "cannot subtract histogram snapshots with different boundaries")
    counts = [int(a) - int(b) for a, b in zip(curr["counts"], prev["counts"])]
    if any(c < 0 for c in counts) or curr["count"] < prev["count"]:
        raise ValueError(
            "histogram delta went backwards: prev is not an earlier "
            "snapshot of curr (registry reset mid-window?)")
    boundaries = list(curr["boundaries"])
    delta = {
        "kind": "histogram",
        "boundaries": boundaries,
        "counts": counts,
        "count": int(curr["count"]) - int(prev["count"]),
        "sum": float(curr["sum"]) - float(prev["sum"]),
        "min": None,
        "max": None,
    }
    if delta["count"]:
        nz = [i for i, c in enumerate(counts) if c]
        lo_i, hi_i = nz[0], nz[-1]
        # window min lies inside bucket lo_i: bound it by the bucket's
        # lower edge (or the lifetime min for the first bucket), window
        # max by the bucket's upper edge (lifetime max for overflow)
        delta["min"] = boundaries[lo_i - 1] if lo_i > 0 else curr["min"]
        delta["max"] = (boundaries[hi_i] if hi_i < len(boundaries)
                        else curr["max"])
        for q in (0.5, 0.9, 0.99):
            delta["p%g" % (q * 100)] = estimate_percentile(delta, q)
    else:
        for q in (0.5, 0.9, 0.99):
            delta["p%g" % (q * 100)] = None
    return delta


def subtract_counter_values(curr: float, prev: float) -> float:
    """Window delta of a monotonic counter; raises if it went backwards."""
    d = float(curr) - float(prev)
    if d < 0:
        raise ValueError(
            f"counter delta went backwards ({curr} < {prev}): prev is not "
            "an earlier snapshot of curr")
    return d


def subtract_registry_snapshots(curr: dict, prev: Optional[dict]) -> dict:
    """Window delta of two full ``MetricRegistry.snapshot()`` documents.

    Counters, monitor values and histogram buckets subtract exactly
    (:func:`subtract_counter_values` / :func:`subtract_histogram_snapshots`
    semantics); gauges are level- not event-valued, so the delta carries
    the *current* gauge reading. A counter/histogram present only in
    ``curr`` deltas from zero (it was created inside the window); one that
    went backwards raises. ``prev=None`` returns the full current view.
    """
    prev = prev or {}
    out: dict = {"counters": {}, "gauges": dict(curr.get("gauges", {})),
                 "histograms": {}}
    pc = prev.get("counters", {})
    for name, v in curr.get("counters", {}).items():
        out["counters"][name] = subtract_counter_values(v, pc.get(name, 0.0))
    ph = prev.get("histograms", {})
    for name, h in curr.get("histograms", {}).items():
        out["histograms"][name] = subtract_histogram_snapshots(
            h, ph.get(name))
    if "monitor" in curr:
        pm = prev.get("monitor", {})
        out["monitor"] = {}
        for name, rep in curr["monitor"].items():
            pv = float(pm.get(name, {}).get("value", 0.0))
            out["monitor"][name] = {
                "value": subtract_counter_values(
                    float(rep.get("value", 0.0)), pv),
                "peak": float(rep.get("peak", 0.0)),
            }
    return out


class MetricRegistry:
    """Thread-safe name -> metric map with get-or-create accessors."""

    def __init__(self, namespace: str = "paddle_tpu"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, description: str = "") -> Counter:
        return self._get_or_create(Counter, name, description=description)

    def gauge(self, name: str, description: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, description=description)

    def histogram(self, name: str, boundaries: Sequence[float] = None,
                  description: str = "") -> Histogram:
        return self._get_or_create(Histogram, name, boundaries=boundaries,
                                   description=description)

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    # ---- snapshots --------------------------------------------------------

    def snapshot(self, include_monitor: bool = True,
                 compact: bool = False) -> dict:
        """Point-in-time view of every metric + absorbed monitor counters.

        ``compact=True`` replaces per-bucket arrays with the summary stats
        (count/sum/min/max/p50/p90/p99) — the right shape for bench rows.
        """
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(self.metrics().items()):
            snap = m.snapshot()
            if m.kind == "histogram":
                if compact:
                    snap = {k: v for k, v in snap.items()
                            if k not in ("boundaries", "counts", "kind")}
                out["histograms"][name] = snap
            elif m.kind == "gauge":
                out["gauges"][name] = snap["value"]
            else:
                out["counters"][name] = snap["value"]
        if include_monitor:
            out["monitor"] = self._monitor_report()
        return out

    @staticmethod
    def _monitor_report() -> dict:
        # Lazy import: core.monitor is stdlib-only too, but keeping it out
        # of module load preserves standalone importability of this file.
        try:
            from paddle_tpu.core import monitor
        except ImportError:  # standalone module load (stdlib-only test)
            return {}
        return {name: dict(rep)
                for name, rep in sorted(monitor.registry().report().items())}

    # ---- Prometheus text exposition ---------------------------------------

    def to_prometheus(self) -> str:
        """Render the registry (+ monitor counters) in Prometheus text
        format 0.0.4: histograms as cumulative ``_bucket{le=...}`` series
        plus ``_sum``/``_count``."""
        lines: List[str] = []
        ns = _sanitize(self.namespace)

        def emit(name, kind, help_, series):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {kind}")
            lines.extend(series)

        for name, m in sorted(self.metrics().items()):
            full = f"{ns}_{_sanitize(name)}"
            help_ = m.description or name
            if m.kind == "histogram":
                snap = m.snapshot()
                series, cum = [], 0
                for b, c in zip(snap["boundaries"], snap["counts"]):
                    cum += c
                    series.append(
                        f'{full}_bucket{{le="{_fmt_le(b)}"}} {cum}')
                cum += snap["counts"][-1]
                series.append(f'{full}_bucket{{le="+Inf"}} {cum}')
                series.append(f"{full}_sum {_fmt_val(snap['sum'])}")
                series.append(f"{full}_count {snap['count']}")
                emit(full, "histogram", help_, series)
            elif m.kind == "gauge":
                emit(full, "gauge", help_, [f"{full} {_fmt_val(m.value)}"])
            else:
                emit(f"{full}_total", "counter", help_,
                     [f"{full}_total {_fmt_val(m.value)}"])
        for name, rep in self._monitor_report().items():
            full = f"{ns}_monitor_{_sanitize(name)}"
            emit(full, "gauge", f"core.monitor stat {name}",
                 [f"{full} {_fmt_val(rep['value'])}"])
            lines.append(f"{full}_peak {_fmt_val(rep['peak'])}")
        return "\n".join(lines) + "\n"

    def to_json(self, compact: bool = False) -> str:
        return json.dumps(self.snapshot(compact=compact), sort_keys=True)


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _fmt_le(b: float) -> str:
    return "%g" % b


def _fmt_val(v: float) -> str:
    f = float(v)
    return "%d" % f if f == int(f) and abs(f) < 1e15 else repr(f)


# ---- process-global default registry (off until enabled) -------------------

_default = MetricRegistry()
_active = False
_state_lock = threading.Lock()


def default_registry() -> MetricRegistry:
    """The process-wide registry (always exists; may be inactive)."""
    return _default


def active_registry() -> Optional[MetricRegistry]:
    """The registry iff metrics are enabled, else None.

    This is the engines' hot-path gate: one module-global read + None
    check per step when metrics are off.
    """
    return _default if _active else None


def enable() -> MetricRegistry:
    global _active
    with _state_lock:
        _active = True
    return _default


def disable() -> None:
    global _active
    with _state_lock:
        _active = False


def reset() -> None:
    """Drop all metrics and deactivate (test isolation)."""
    global _default, _active
    with _state_lock:
        _default = MetricRegistry()
        _active = False

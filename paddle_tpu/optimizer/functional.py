"""Pure functional optimizer update rules.

Design: one pure function per rule, usable (a) eagerly per-parameter by the dygraph Optimizer
below (jit-cached by shape) and (b) over whole parameter pytrees inside a pjit'd train step by
the distributed engine — the same math in both worlds, the analogue of phi's adam kernels
(paddle/phi/kernels/gpu/adam_kernel.cu) without a second implementation.

All rules keep master weights implicitly: state is stored in f32 even for bf16 params.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def init_state(rule: str, param):
    f32 = jnp.float32
    z = lambda: jnp.zeros_like(param, f32)
    if rule == "sgd":
        return ()
    if rule == "momentum":
        return (z(),)
    if rule in ("adam", "adamw"):
        return (z(), z())  # m, v
    if rule == "adamax":
        return (z(), z())  # m, inf-norm
    if rule == "adagrad":
        return (z(),)
    if rule == "adadelta":
        return (z(), z())  # avg sq grad, avg sq update
    if rule == "rmsprop":
        return (z(), z(), z())  # mean_sq, mean, momentum
    if rule == "lamb":
        return (z(), z())
    if rule == "lars":
        return (z(),)
    raise ValueError(rule)


def clip_grads(grads: dict, clip):
    """Apply a paddle grad-clip rule over a name->grad dict (traced-safe).

    The analogue of ClipGradByGlobalNorm/_ByNorm/_ByValue application inside the
    fused step (reference python/paddle/fluid/clip.py); shared by the pjit engine
    and the static Executor lowering."""
    from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

    if clip is None:
        return grads
    if isinstance(clip, ClipGradByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grads.values()))
        scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        return {n: (g * scale).astype(g.dtype) for n, g in grads.items()}
    if isinstance(clip, ClipGradByNorm):
        return {
            n: (g * jnp.minimum(
                clip.clip_norm / jnp.maximum(
                    jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32)))), 1e-12),
                1.0)).astype(g.dtype)
            for n, g in grads.items()}
    if isinstance(clip, ClipGradByValue):
        return {n: jnp.clip(g, clip.min, clip.max) for n, g in grads.items()}
    return grads


def sgd(param, grad, state, *, lr, weight_decay=0.0):
    g = grad.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * param.astype(jnp.float32)
    new_p = param.astype(jnp.float32) - lr * g
    return new_p.astype(param.dtype), ()


def momentum(param, grad, state, *, lr, momentum=0.9, weight_decay=0.0, use_nesterov=False):
    (vel,) = state
    g = grad.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * param.astype(jnp.float32)
    vel = momentum * vel + g
    if use_nesterov:
        update = g + momentum * vel
    else:
        update = vel
    new_p = param.astype(jnp.float32) - lr * update
    return new_p.astype(param.dtype), (vel,)


def adam(param, grad, state, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, step,
         weight_decay=0.0, lazy_mode=False):
    m, v = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if weight_decay:  # L2 reg (paddle Adam regularization semantics)
        g = g + weight_decay * p32
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    bc1 = 1 - beta1 ** step
    bc2 = 1 - beta2 ** step
    m_hat = m / bc1
    v_hat = v / bc2
    new_p = p32 - lr * m_hat / (jnp.sqrt(v_hat) + epsilon)
    return new_p.astype(param.dtype), (m, v)


def adamw(param, grad, state, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, step,
          weight_decay=0.01, lr_ratio=1.0):
    m, v = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    m_hat = m / (1 - beta1 ** step)
    v_hat = v / (1 - beta2 ** step)
    # decoupled decay (AdamW): p -= lr * (m_hat/(sqrt(v_hat)+eps) + wd * p)
    new_p = p32 - lr * lr_ratio * (m_hat / (jnp.sqrt(v_hat) + epsilon) + weight_decay * p32)
    return new_p.astype(param.dtype), (m, v)


def adamax(param, grad, state, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-8, step,
           weight_decay=0.0):
    m, u = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p32
    m = beta1 * m + (1 - beta1) * g
    u = jnp.maximum(beta2 * u, jnp.abs(g))
    new_p = p32 - (lr / (1 - beta1 ** step)) * m / (u + epsilon)
    return new_p.astype(param.dtype), (m, u)


def adagrad(param, grad, state, *, lr, epsilon=1e-6, weight_decay=0.0, initial_accumulator_value=0.0):
    (acc,) = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p32
    acc = acc + jnp.square(g)
    new_p = p32 - lr * g / (jnp.sqrt(acc) + epsilon)
    return new_p.astype(param.dtype), (acc,)


def adadelta(param, grad, state, *, lr=1.0, rho=0.95, epsilon=1e-6, weight_decay=0.0):
    avg_sq_grad, avg_sq_update = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p32
    avg_sq_grad = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = jnp.sqrt(avg_sq_update + epsilon) / jnp.sqrt(avg_sq_grad + epsilon) * g
    avg_sq_update = rho * avg_sq_update + (1 - rho) * jnp.square(update)
    new_p = p32 - lr * update
    return new_p.astype(param.dtype), (avg_sq_grad, avg_sq_update)


def rmsprop(param, grad, state, *, lr, rho=0.95, epsilon=1e-6, momentum=0.0,
            centered=False, weight_decay=0.0):
    mean_sq, mean_g, mom = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p32
    mean_sq = rho * mean_sq + (1 - rho) * jnp.square(g)
    if centered:
        mean_g = rho * mean_g + (1 - rho) * g
        denom = jnp.sqrt(mean_sq - jnp.square(mean_g) + epsilon)
    else:
        denom = jnp.sqrt(mean_sq + epsilon)
    mom = momentum * mom + lr * g / denom
    new_p = p32 - mom
    return new_p.astype(param.dtype), (mean_sq, mean_g, mom)


def lamb(param, grad, state, *, lr, beta1=0.9, beta2=0.999, epsilon=1e-6, step,
         lamb_weight_decay=0.01, exclude_from_decay=False):
    m, v = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    m = beta1 * m + (1 - beta1) * g
    v = beta2 * v + (1 - beta2) * jnp.square(g)
    m_hat = m / (1 - beta1 ** step)
    v_hat = v / (1 - beta2 ** step)
    r = m_hat / (jnp.sqrt(v_hat) + epsilon)
    if not exclude_from_decay:
        r = r + lamb_weight_decay * p32
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where(w_norm > 0, jnp.where(r_norm > 0, w_norm / r_norm, 1.0), 1.0)
    new_p = p32 - lr * trust * r
    return new_p.astype(param.dtype), (m, v)


def lars(param, grad, state, *, lr, momentum=0.9, lars_coeff=0.001,
         lars_weight_decay=0.0005, epsilon=0.0, exclude_from_decay=False):
    """LARS (reference: operators/optimizers/lars_momentum_op): layerwise lr =
    lars_coeff * ||w|| / (||g|| + wd * ||w|| + eps), momentum applied after."""
    (vel,) = state
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    wd = 0.0 if exclude_from_decay else lars_weight_decay
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (w_norm > 0) & (g_norm > 0),
        lars_coeff * w_norm / (g_norm + wd * w_norm + epsilon), 1.0)
    d = g + wd * p32
    vel = momentum * vel + lr * local_lr * d
    new_p = p32 - vel
    return new_p.astype(param.dtype), (vel,)


RULES = {
    "sgd": sgd, "momentum": momentum, "adam": adam, "adamw": adamw,
    "adamax": adamax, "adagrad": adagrad, "adadelta": adadelta,
    "rmsprop": rmsprop, "lamb": lamb, "lars": lars,
}

_NEEDS_STEP = {"adam", "adamw", "adamax", "lamb"}

# jit-cached per (rule, static hyperparams); lr and step stay dynamic so LR schedules
# don't retrigger compilation — the eager fast path.
_jitted_cache = {}


def jitted_rule(rule: str, **static_kwargs):
    key = (rule, tuple(sorted(static_kwargs.items())))
    if key not in _jitted_cache:
        fn = RULES[rule]
        needs_step = rule in _NEEDS_STEP

        def wrapped(param, grad, state, lr, step):
            kw = dict(static_kwargs)
            if needs_step:
                kw["step"] = step
            return fn(param, grad, state, lr=lr, **kw)

        _jitted_cache[key] = jax.jit(wrapped)
    return _jitted_cache[key]


def make_tree_update(optimizer, param_objs):
    """Build update(params, grads, opt_state, lr, step_i) -> (new_params, new_opt)
    for a dict of named parameters, honoring the optimizer's per-param rule
    kwargs (weight-decay exclusion via apply_decay_param_fun, lamb exclusions).
    Shared by TrainStepEngine and auto_parallel.Engine so the traced update
    logic exists exactly once."""
    rule = RULES[optimizer._rule]
    needs_step = optimizer._rule in _NEEDS_STEP
    kwargs_by_name = {n: optimizer._rule_kwargs(p) for n, p in param_objs.items()}

    def update(params, grads, opt_state, lr, step_i):
        new_params, new_opt = {}, {}
        for n, p in params.items():
            kw = dict(kwargs_by_name[n])
            if needs_step:
                kw["step"] = step_i
            new_params[n], new_opt[n] = rule(p, grads[n], opt_state[n], lr=lr, **kw)
        return new_params, new_opt

    return update

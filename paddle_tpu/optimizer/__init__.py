"""Optimizers. Reference: python/paddle/optimizer/optimizer.py + adam.py etc.

The dygraph Optimizer reads `p.grad`, runs the jit-cached functional rule per parameter, and
swaps `p._data` in place (buffer donation analogue). The same rules run over whole pytrees
inside the distributed engine's pjit'd train step (optimizer/functional.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from ..nn.layer import Parameter
from . import functional as funct
from . import lr  # noqa: F401
from .lr import LRScheduler


class Optimizer:
    _rule = "sgd"
    _hyper = {}
    # offload (reference group_sharded_optimizer_stage2.py:48 offload=True):
    # eager-mode optimizer states are pulled to host RAM (numpy) after every
    # update, so only params+grads stay device-resident between steps. Set via
    # GroupShardedOptimizerStage2(..., offload=True); the pjit engine instead
    # maps this to pinned_host memory-kind shardings.
    _offload = False

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        # parameters=None is legal in static mode (minimize binds the program's
        # captured Parameters at lowering); dygraph step() requires them
        self._parameter_list = list(parameters) if parameters is not None else []
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        wd = weight_decay
        self._l1_decay = None
        if wd is None:
            wd = 0.0
        elif not isinstance(wd, float):
            if getattr(wd, "_is_l1", False):
                # L1Decay as global weight_decay: applied as a grad penalty in
                # step(), NOT folded into the rules' (L2) weight_decay
                self._l1_decay = wd
                wd = 0.0
            else:
                # L2Decay object parity
                wd = float(getattr(wd, "_coeff", getattr(wd, "coeff", wd)))
        self._weight_decay = wd
        self._states = {}  # id(param) -> state tuple
        self._step_count = 0
        self._apply_decay_param_fun = kwargs.pop("apply_decay_param_fun", None)

    # ---- lr ----
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)

    def _lr_step(self):
        pass  # schedulers are stepped by user code (paddle semantics)

    # ---- core ----
    def _rule_kwargs(self, param):
        """Static hyperparams for the functional rule; per-param wd exclusion hook."""
        kw = dict(self._hyper)
        wd = self._weight_decay
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(param.name):
            wd = 0.0
        if self._rule in ("sgd", "momentum", "adam", "adamax", "adagrad", "adadelta",
                          "rmsprop", "adamw"):
            kw["weight_decay"] = wd
        return kw

    @no_grad()
    def step(self):
        if not self._parameter_list:
            raise ValueError(
                "optimizer has no parameters; pass `parameters=` for dygraph use "
                "(parameters=None is only valid with static-mode minimize)")
        self._step_count += 1
        lr_val = self.get_lr()
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if not p.stop_gradient and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            if g is None:
                continue
            # L1 regularization (per-param ParamAttr(regularizer=L1Decay) or
            # optimizer-level weight_decay=L1Decay): grad += coeff*sign(param)
            # — the l1_decay op of the reference
            reg = getattr(p, "regularizer", None)
            if reg is None or not getattr(reg, "_is_l1", False):
                reg = self._l1_decay
            if reg is not None and getattr(reg, "_is_l1", False):
                from ..core.tensor import Tensor as _T

                g = _T(reg.apply(p, g._data))
            st = self._states.get(id(p))
            if st is None:
                st = funct.init_state(self._rule, p._data)
                self._states[id(p)] = (p, st)
            else:
                st = st[1]
            rule = funct.jitted_rule(self._rule, **self._rule_kwargs(p))
            new_data, new_state = rule(p._data, g._data, st,
                                       jnp.float32(lr_val), jnp.int32(self._step_count))
            p._data = new_data
            if self._offload:  # host-resident between steps (frees HBM)
                import numpy as _np

                new_state = tuple(_np.asarray(s) for s in new_state)
            self._states[id(p)] = (p, new_state)

    minimize_step = step

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p._grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        if getattr(loss, "is_symbolic", False):
            # static mode: attach the train spec; Executor lowers backward +
            # update via jax.grad at compile time (append_backward analogue)
            loss.block.program._train = (loss.name, self)
            return None, []
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    # ---- checkpoint ----
    def state_dict(self):
        out = {"_step_count": self._step_count}
        for i, p in enumerate(self._parameter_list):
            entry = self._states.get(id(p))
            if entry is not None:
                for j, s in enumerate(entry[1]):
                    out[f"param{i}_state{j}"] = Tensor(jnp.asarray(s))
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("_step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._parameter_list):
            states = []
            j = 0
            while f"param{i}_state{j}" in state_dict:
                s = state_dict[f"param{i}_state{j}"]
                states.append(s._data if isinstance(s, Tensor) else jnp.asarray(s))
                j += 1
            if states:
                self._states[id(p)] = (p, tuple(states))

    set_dict = set_state_dict


class SGD(Optimizer):
    _rule = "sgd"


class Momentum(Optimizer):
    _rule = "momentum"

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        self._hyper = {"momentum": momentum, "use_nesterov": use_nesterov}


class Adam(Optimizer):
    _rule = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        def _v(b):
            return float(b.item()) if isinstance(b, Tensor) else float(b)
        self._hyper = {"beta1": _v(beta1), "beta2": _v(beta2), "epsilon": float(epsilon)}


class AdamW(Optimizer):
    _rule = "adamw"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name,
                         apply_decay_param_fun=apply_decay_param_fun, **kw)
        def _v(b):
            return float(b.item()) if isinstance(b, Tensor) else float(b)
        self._hyper = {"beta1": _v(beta1), "beta2": _v(beta2), "epsilon": float(epsilon)}


class Adamax(Optimizer):
    _rule = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon}


class Adagrad(Optimizer):
    _rule = "adagrad"

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        self._hyper = {"epsilon": epsilon}


class Adadelta(Optimizer):
    _rule = "adadelta"

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        self._hyper = {"epsilon": epsilon, "rho": rho}


class RMSProp(Optimizer):
    _rule = "rmsprop"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name, **kw)
        self._hyper = {"rho": rho, "epsilon": epsilon, "momentum": momentum,
                       "centered": centered}


class Lars(Optimizer):
    """LARS momentum (reference: fluid.optimizer.LarsMomentumOptimizer /
    operators/optimizers/lars_momentum_op)."""

    _rule = "lars"

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name, **kw)
        self._hyper = {"momentum": momentum, "lars_coeff": lars_coeff,
                       "lars_weight_decay": lars_weight_decay, "epsilon": epsilon}
        self._exclude_names = list(exclude_from_weight_decay or [])

    def _rule_kwargs(self, param):
        kw = dict(self._hyper)
        pname = getattr(param, "name", "") or ""
        if any(s in pname for s in self._exclude_names):
            kw["exclude_from_decay"] = True
        return kw


LarsMomentum = Lars


class Lamb(Optimizer):
    _rule = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name, **kw)
        self._hyper = {"beta1": beta1, "beta2": beta2, "epsilon": epsilon,
                       "lamb_weight_decay": lamb_weight_decay}
        self._exclude_fn = exclude_from_weight_decay_fn

    def _rule_kwargs(self, param):
        kw = dict(self._hyper)
        if self._exclude_fn is not None and self._exclude_fn(param):
            kw["exclude_from_decay"] = True
        return kw

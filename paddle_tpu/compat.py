"""paddle.compat shims (reference python/paddle/compat.py)."""


def to_text(obj, encoding="utf-8"):
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    if isinstance(obj, (list, set, tuple)):
        return type(obj)(to_text(o, encoding) for o in obj)
    return obj


def to_bytes(obj, encoding="utf-8"):
    if isinstance(obj, str):
        return obj.encode(encoding)
    if isinstance(obj, (list, set, tuple)):
        return type(obj)(to_bytes(o, encoding) for o in obj)
    return obj


def get_exception_message(exc):
    return str(exc)


def round(x, d=0):
    """py2-style round: half away from zero, returns float (the reference
    shim's whole purpose; python3's builtin does banker's rounding)."""
    import math

    p = 10 ** d
    if x >= 0.0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    return float(math.ceil((x * p) + math.copysign(0.5, x))) / p

"""paddle.incubate extras. Reference: python/paddle/incubate/ (#54) —
ASP (2:4 structured sparsity), LookAhead and ModelAverage optimizers."""
from . import asp
from .optimizer import LookAhead, ModelAverage

__all__ = ["asp", "LookAhead", "ModelAverage"]

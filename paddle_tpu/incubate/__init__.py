"""paddle.incubate extras. Reference: python/paddle/incubate/ (#54) — ASP,
LookAhead/ModelAverage, fused transformer layers, softmax-mask fusions, graph
ops, segment reductions, functional autograd, auto checkpoint, shared-memory
multiprocessing."""
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import checkpoint  # noqa: F401
from . import quantization  # noqa: F401
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors, graph_send_recv,
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle,
)
from .tensor import segment_max, segment_mean, segment_min, segment_sum  # noqa: F401

__all__ = [
    "asp", "autotune", "LookAhead", "ModelAverage", "nn", "autograd", "checkpoint",
    "softmax_mask_fuse_upper_triangle", "softmax_mask_fuse", "graph_send_recv",
    "graph_khop_sampler", "graph_sample_neighbors", "graph_reindex",
    "segment_sum", "segment_mean", "segment_max", "segment_min",
]

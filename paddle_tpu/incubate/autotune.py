"""paddle.incubate.autotune — user-facing autotune switch.

Reference: python/paddle/incubate/autotune.py `set_config` (kernel/layout/
dataloader sections; kernel tuning backed by phi's AlgorithmsCache +
switch_autotune). Here the kernel section drives the Pallas block-size tuner
in core/autotune.py; layout tuning has no TPU meaning (XLA owns layouts) and
is accepted as a no-op for API compatibility.
"""
from __future__ import annotations

from ..core import autotune as _core

__all__ = ["set_config"]


def set_config(config=None):
    _core.set_config(config)


def kernel_cache():
    """Expose cache stats (hit rate / size) like phi's autotune status."""
    return _core.cache()

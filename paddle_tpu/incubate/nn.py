"""incubate.nn fused transformer layers.

Reference: python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention / FusedFeedForward / FusedTransformerEncoderLayer
backed by the hand-fused CUDA kernels (operators/fused/fused_attention_op.cu,
fused_feedforward_op.cu). TPU-native: "fused" means the whole block traces
into one XLA computation — layernorm/bias/residual/dropout fuse into the
matmuls automatically, and the attention core routes to the Pallas flash
kernel — so these layers share code with nn.MultiHeadAttention-level ops but
keep the reference's fused-layer API (normalize_before, single qkv weight,
epilogue residual+dropout inside the layer)."""
from __future__ import annotations

import math

from .. import nn as base_nn
from ..nn import functional as F
from ..nn.layer import Layer
from ..ops import manipulation as P


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 qkv_bias_attr=None, linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        # reference layout: qkv_weight [3, heads, head_dim, embed]
        self.qkv_weight = self.create_parameter(
            (3 * embed_dim, embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = self.create_parameter(
            (3 * embed_dim,), attr=qkv_bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=linear_bias_attr, is_bias=True)
        self.ln = base_nn.LayerNorm(embed_dim, epsilon=epsilon)

    def forward(self, x, attn_mask=None, cache=None):
        # delegate to the functional form (incubate.nn.functional) — one
        # implementation of the block; this layer stores qkv as [3e, e] and
        # the functional form takes the reference's [3, nh, hd, e] layout
        from .nn_functional import fused_multi_head_attention

        qkv_w = P.reshape(self.qkv_weight,
                          (3, self.num_heads, self.head_dim, self.embed_dim))
        qkv_b = P.reshape(self.qkv_bias,
                          (3, self.num_heads, self.head_dim))
        return fused_multi_head_attention(
            x, qkv_w, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.ln.weight, pre_ln_bias=self.ln.bias,
            ln_scale=self.ln.weight, ln_bias=self.ln.bias,
            pre_ln_epsilon=self.ln._epsilon, ln_epsilon=self.ln._epsilon,
            qkv_bias=qkv_b, linear_bias=self.linear_bias,
            cache_kv=cache, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            training=self.training)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.linear1 = base_nn.Linear(d_model, dim_feedforward,
                                      weight_attr=linear1_weight_attr,
                                      bias_attr=linear1_bias_attr)
        self.linear2 = base_nn.Linear(dim_feedforward, d_model,
                                      weight_attr=linear2_weight_attr,
                                      bias_attr=linear2_bias_attr)
        self.ln = base_nn.LayerNorm(d_model, epsilon=epsilon)

    def forward(self, x):
        # delegate to the functional form — one implementation of the block
        from .nn_functional import fused_feedforward

        return fused_feedforward(
            x, self.linear1.weight, self.linear2.weight,
            linear1_bias=self.linear1.bias, linear2_bias=self.linear2.bias,
            ln1_scale=self.ln.weight, ln1_bias=self.ln.bias,
            ln2_scale=self.ln.weight, ln2_bias=self.ln.bias,
            ln1_epsilon=self.ln._epsilon, ln2_epsilon=self.ln._epsilon,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation, pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


# `paddle.incubate.nn.functional` (reference incubate/nn/functional/
# fused_transformer.py): functional forms of the fused blocks above. Alias
# so both attribute access and `import paddle_tpu.incubate.nn.functional`
# resolve even though `nn` here is a module, not a package.
from . import nn_functional as functional  # noqa: E402,F401
import sys as _sys

_sys.modules[__name__ + ".functional"] = functional
del _sys

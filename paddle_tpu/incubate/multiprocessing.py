"""Shared-memory tensor transfer between processes.

Reference: python/paddle/incubate/multiprocessing/reductions.py — registers
ForkingPickler reducers so Tensors cross process boundaries through shared
memory instead of pickled copies (the DataLoader workers' transport).
TPU-native: device buffers are host-fetched once, the host copy rides
multiprocessing.shared_memory, and the receiver re-wraps without another copy."""
from __future__ import annotations

import multiprocessing.reduction as _reduction
from multiprocessing import shared_memory

import numpy as np

from ..core.tensor import Tensor

_KEEPALIVE = {}


def _rebuild_tensor(shm_name, shape, dtype_str):
    shm = shared_memory.SharedMemory(name=shm_name)
    arr = np.ndarray(shape, dtype=np.dtype(dtype_str), buffer=shm.buf)
    t = Tensor(np.array(arr))  # own the data; shm can be released
    shm.close()
    try:
        shm_owner = _KEEPALIVE.pop(shm_name, None)
        if shm_owner is not None:
            shm_owner.unlink()
    except FileNotFoundError:
        pass
    return t


def _reduce_tensor(t: Tensor):
    arr = np.asarray(t.numpy())
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    dst = np.ndarray(arr.shape, dtype=arr.dtype, buffer=shm.buf)
    dst[...] = arr
    _KEEPALIVE[shm.name] = shm
    return _rebuild_tensor, (shm.name, arr.shape, arr.dtype.str)


def init_reductions():
    """Install the Tensor reducer into ForkingPickler (call once per process;
    the reference does this at import of paddle.incubate.multiprocessing)."""
    _reduction.ForkingPickler.register(Tensor, _reduce_tensor)


init_reductions()

"""Int8 inference quantization — the TPU-native analogue of the reference's
slim quantization stack.

Reference: python/paddle/fluid/contrib/slim/quantization/
(post_training_quantization.py, quantization_pass.py — per-channel weight
scales via abs-max, activation quant passes, int8 kernels through MKLDNN/
TensorRT). On TPU the int8 path is the MXU itself: v5e runs s8 x s8 -> s32
matmuls at 2x the bf16 rate, so quantization here produces jnp arrays and a
dot_general with preferred_element_type=int32 — no vendor kernel library.

Two modes:
- weight_only_int8: weights stored s8 + per-output-channel f32 scale,
  dequantized into the matmul's bf16 input on the fly. Halves weight HBM
  traffic (the binding constraint of autoregressive decode) with unchanged
  activation numerics.
- dynamic_int8: per-row abs-max quantization of activations at runtime +
  s8 x s8 -> s32 MXU matmul, rescaled by (row_scale x channel_scale).
  The reference's dynamic quantization strategy, without calibration data.

Surface:
  quantize_weight(w)              -> (w_int8, scale)        [per out-channel]
  weight_only_int8_matmul(x, wq, scale [, bias])
  dynamic_int8_matmul(x, wq, scale [, bias])
  QuantizedLinear.from_linear(linear, mode=...)  drop-in nn.Layer
  quantize_model(layer, mode=...) swap every nn.Linear in place
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn import Layer

__all__ = ["quantize_weight", "weight_only_int8_matmul",
           "dynamic_int8_matmul", "static_int8_matmul", "QuantizedLinear",
           "quantize_model", "fake_quant", "fake_quant_array", "QATLinear",
           "ImperativeQuantAware", "PostTrainingQuantization"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _as_t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x),
                                                  stop_gradient=True)


def quantize_weight(w):
    """[in, out] float weight -> (s8 weight, [out] f32 scale), abs-max per
    output channel (quantization_pass.py's channel_wise_abs_max)."""
    w = _arr(w)
    scale = jnp.max(jnp.abs(w), axis=0) / 127.0
    safe = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / safe), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def weight_only_int8_matmul(x, w_int8, scale, bias=None):
    """x [.., in] @ dequant(w_int8 [in, out]) + bias. The dequant multiply
    fuses into the matmul's weight read under XLA — HBM sees s8. Routed
    through the dispatch layer under the white-listed "linear" op name so
    amp autocast applies to the activation exactly as for nn.Linear."""
    from ..core.dispatch import apply

    def kernel(a, w, s, *rest):
        wd = w.astype(a.dtype) * s.astype(a.dtype)
        out = a @ wd
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out

    args = [_as_t(x), _as_t(w_int8), _as_t(scale)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply("linear", kernel, args,
                 nondiff_mask=[False, True, False, False][:len(args)])


def dynamic_int8_matmul(x, w_int8, scale, bias=None):
    """Per-row dynamic activation quantization + s8 x s8 -> s32 MXU matmul.
    out = (x_q @ w_q) * x_scale[:, None] * w_scale[None, :] (+ bias).
    Dispatch-routed like weight_only_int8_matmul (the quantize step itself
    fixes the matmul precision, so amp only affects the epilogue dtype)."""
    from ..core.dispatch import apply

    def kernel(a, wq, s, *rest):
        lead = a.shape[:-1]
        x2 = a.reshape((-1, a.shape[-1]))
        x_scale = jnp.max(jnp.abs(x2), axis=1, keepdims=True) / 127.0
        safe = jnp.where(x_scale == 0, 1.0, x_scale)
        x_q = jnp.clip(jnp.round(x2 / safe), -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            x_q, wq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = (acc.astype(jnp.float32) * x_scale.astype(jnp.float32)
               * s.astype(jnp.float32)[None, :]).astype(a.dtype)
        out = out.reshape(lead + (out.shape[-1],))
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out

    args = [_as_t(x), _as_t(w_int8), _as_t(scale)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply("linear", kernel, args,
                 nondiff_mask=[False, True, False, False][:len(args)])


def static_int8_matmul(x, w_int8, scale, act_scale, bias=None):
    """Calibrated static activation quantization: x quantized with the FIXED
    per-layer scale recorded during PTQ calibration (the reference's
    out_threshold), then s8 x s8 -> s32 on the MXU. Unlike dynamic_int8
    there is no runtime abs-max reduction over the activation."""
    from ..core.dispatch import apply

    def kernel(a, wq, s, act_s, *rest):
        lead = a.shape[:-1]
        x2 = a.reshape((-1, a.shape[-1]))
        sc = jnp.where(act_s == 0, 1.0, act_s).astype(jnp.float32)
        x_q = jnp.clip(jnp.round(x2 / sc.astype(x2.dtype)),
                       -127, 127).astype(jnp.int8)
        acc = jax.lax.dot_general(
            x_q, wq, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = (acc.astype(jnp.float32) * sc
               * s.astype(jnp.float32)[None, :]).astype(a.dtype)
        out = out.reshape(lead + (out.shape[-1],))
        if rest:
            out = out + rest[0].astype(out.dtype)
        return out

    args = [_as_t(x), _as_t(w_int8), _as_t(scale), _as_t(act_scale)]
    if bias is not None:
        args.append(_as_t(bias))
    return apply("linear", kernel, args,
                 nondiff_mask=[False, True, False, False, False][:len(args)])


class QuantizedLinear(Layer):
    """Drop-in for nn.Linear built from a trained layer's weights."""

    MODES = ("weight_only_int8", "dynamic_int8", "static_int8")

    def __init__(self, w_int8, scale, bias=None, mode="weight_only_int8",
                 act_scale=None):
        super().__init__()
        if mode not in self.MODES:
            raise ValueError(f"mode must be one of {self.MODES}, got {mode!r}")
        if mode == "static_int8" and act_scale is None:
            raise ValueError(
                "static_int8 needs the calibrated act_scale "
                "(PostTrainingQuantization.collect records it)")
        self.mode = mode
        # persistable BUFFERS, not Parameters: not trainable (absent from
        # parameters()) but they must flow through state_dict — paddle.save
        # must keep them, and generate()'s functional_call must receive them
        # as traced runtime arguments, never bake them into the executable
        # as constants (which would let XLA fold the dequant into a
        # full-precision weight and defeat the s8-in-HBM point)
        self.register_buffer("_w_int8", Tensor(_arr(w_int8)))
        self.register_buffer("_scale", Tensor(_arr(scale)))
        self._bias_none = bias is None
        if bias is not None:
            self.register_buffer("_bias", Tensor(_arr(bias)))
        if act_scale is not None:
            self.register_buffer(
                "_act_scale", Tensor(jnp.asarray(act_scale, jnp.float32)))

    @classmethod
    def from_linear(cls, linear, mode="weight_only_int8", act_scale=None):
        q, scale = quantize_weight(linear.weight)
        bias = getattr(linear, "bias", None)
        return cls(q, scale, bias=None if bias is None else bias._data,
                   mode=mode, act_scale=act_scale)

    def forward(self, x):
        bias = None if self._bias_none else self._bias
        if self.mode == "static_int8":
            return static_int8_matmul(x, self._w_int8, self._scale,
                                      self._act_scale, bias=bias)
        fn = (weight_only_int8_matmul if self.mode == "weight_only_int8"
              else dynamic_int8_matmul)
        return fn(x, self._w_int8, self._scale, bias=bias)


def _linear_kinds():
    """Layer classes eligible for quantization swaps. The TP layers
    (Column/RowParallelLinear — what the model zoo's transformer blocks
    use) are included only in the single-replica case: under mp > 1 their
    forward carries sharding constraints/collectives that the plain
    quantized matmul would drop."""
    from ..distributed.mesh import get_hybrid_communicate_group
    from ..distributed.meta_parallel.mp_layers import (ColumnParallelLinear,
                                                      RowParallelLinear)
    from ..nn import Linear

    hcg = get_hybrid_communicate_group()
    if hcg is None or hcg.degrees["mp"] <= 1:
        return (Linear, ColumnParallelLinear, RowParallelLinear)
    return (Linear,)


def _swap_sublayers(layer, match, make):
    """One walker for every quantization swap: replace each sublayer
    matching `match` with `make(sublayer, name)`, without descending into
    already wrapped layers (QATLinear holds an inner Linear that must never
    be re-swapped out from under it). Returns the (possibly replaced)
    root; the root itself is addressed by name ""."""
    if match(layer):
        return make(layer, "")
    for name, sub in list(layer.named_sublayers()):
        parts = name.split(".")
        parent = layer
        skip = False
        for pth in parts[:-1]:
            parent = getattr(parent, pth)
            if isinstance(parent, (QATLinear, QuantizedLinear)):
                skip = True
                break
        if skip or not match(sub):
            continue
        setattr(parent, parts[-1], make(sub, name))
    return layer


def quantize_model(layer, mode="weight_only_int8", act_scales=None):
    """Swap every Linear-shaped sublayer for a QuantizedLinear in place and
    return the layer (post-training, weight-only by default — the
    reference's PostTrainingQuantization applied the TPU way). QAT-wrapped
    layers (QATLinear) convert via their trained inner Linear. act_scales
    (name -> f32, from PostTrainingQuantization.collect) feeds the
    static_int8 mode."""
    if mode == "static_int8" and not act_scales:
        raise ValueError(
            "static_int8 needs act_scales from a calibration pass "
            "(use PostTrainingQuantization)")
    kinds = _linear_kinds()

    def match(sub):
        return isinstance(sub, kinds + (QATLinear,))

    def make(sub, name):
        inner = sub.inner if isinstance(sub, QATLinear) else sub
        act = None if act_scales is None else act_scales.get(name)
        return QuantizedLinear.from_linear(inner, mode, act_scale=act)

    return _swap_sublayers(layer, match, make)


# --------------------------------------------------------------------- QAT ---

def fake_quant_array(a, bits=8, scale=None, channel_axis=None):
    """Raw-array STE quantize-dequantize (shared by the eager fake_quant op
    below and the static-graph int8_fake_quantize pass)."""
    qmax = float(2 ** (bits - 1) - 1)
    if channel_axis is None:
        dyn = jnp.max(jnp.abs(a)) / qmax
    else:
        axes = tuple(i for i in range(a.ndim) if i != channel_axis % a.ndim)
        dyn = jnp.max(jnp.abs(a), axis=axes, keepdims=True) / qmax
    sc = jnp.where(scale > 0, scale, dyn) if scale is not None else dyn
    sc = jnp.where(sc == 0, 1.0, sc).astype(a.dtype)
    q = jnp.clip(jnp.round(a / sc), -qmax, qmax) * sc
    # straight-through: forward quantized value, identity gradient
    return a + jax.lax.stop_gradient(q - a)


def fake_quant(x, bits=8, scale=None, channel_axis=None):
    """Quantize-dequantize with a straight-through gradient (the reference's
    fake_quantize_dequantize_abs_max op, quantization_pass.py): forward
    rounds onto the int grid, backward passes gradients through unchanged.
    scale=None (or a scale holding 0 — the never-calibrated sentinel) falls
    back to dynamic abs-max INSIDE the kernel, so the choice is trace-safe
    and survives checkpoint restore. channel_axis selects per-channel
    abs-max (the grid deployment uses — quantize_weight is per output
    channel, and QAT must train against the same noise)."""
    from ..core.dispatch import apply

    def kernel(a, *s):
        return fake_quant_array(a, bits, scale=s[0] if s else None,
                                channel_axis=channel_axis)

    args = [_as_t(x)] + ([_as_t(scale)] if scale is not None else [])
    return apply("fake_quant", kernel, args)


class QATLinear(Layer):
    """Linear with fake-quantized weight and activation — trains in float
    with quantization noise so post-training int8 conversion loses nothing
    (reference imperative/qat.py QuantizedLinear). Activation scale follows
    a moving average of abs-max (moving_average_abs_max); "never
    calibrated" is encoded as scale == 0 IN the persisted buffer, so
    restored checkpoints keep their calibration."""

    def __init__(self, linear, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = linear
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate
        self.register_buffer("_act_scale", Tensor(jnp.zeros((), jnp.float32)),
                             persistable=True)

    def forward(self, x):
        from ..jit import in_jit_trace
        from ..nn import functional as F

        qmax = float(2 ** (self.activation_bits - 1) - 1)
        if self.training and not in_jit_trace():
            # moving-average abs-max tracked host-side, OUTSIDE the traced
            # graph (reference moving_average_abs_max state vars). Inside a
            # trace (engine/jit) the frozen scale from eager steps is used.
            cur = float(jnp.max(jnp.abs(_arr(x)))) / qmax
            prev = float(self._act_scale._data)
            new = cur if prev == 0 else \
                self.moving_rate * prev + (1 - self.moving_rate) * cur
            self._act_scale._data = jnp.asarray(new, jnp.float32)
        # scale == 0 -> in-kernel dynamic fallback (never-calibrated case)
        xq = fake_quant(x, self.activation_bits, scale=self._act_scale)
        # per-OUTPUT-channel weight grid, matching quantize_weight's
        # deployment grid (weight layout [in, out] -> channel_axis -1)
        wq = fake_quant(self.inner.weight, self.weight_bits, channel_axis=-1)
        return F.linear(xq, wq, self.inner.bias)


class ImperativeQuantAware:
    """QAT driver (reference imperative/qat.py:42): quantize(model) swaps
    Linear-shaped layers (incl. single-replica TP layers) for QATLinear in
    place; after training, convert(model, mode=...) produces true-int8
    QuantizedLinear layers. mode="dynamic_int8" re-derives activation
    scales per row at runtime (the trained moving average regularized
    training; deployment stays calibration-free, like the reference's
    dynamic strategy); the default keeps activations in float."""

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model):
        kinds = _linear_kinds()
        return _swap_sublayers(
            model, lambda sub: isinstance(sub, kinds),
            lambda lin, name: QATLinear(lin, self.weight_bits,
                                        self.activation_bits,
                                        self.moving_rate))

    def convert(self, model, mode="weight_only_int8"):
        """QATLinear -> real int8 QuantizedLinear (weights re-quantized
        from the trained floats; static_int8 consumes each layer's trained
        moving-average activation scale)."""
        def make(q, name):
            act = float(q._act_scale._data) if mode == "static_int8" else None
            return QuantizedLinear.from_linear(q.inner, mode, act_scale=act)

        return _swap_sublayers(
            model, lambda sub: isinstance(sub, QATLinear), make)


class PostTrainingQuantization:
    """Calibration-based PTQ (reference post_training_quantization.py): run
    representative batches through the model, record per-layer activation
    abs-max, then deploy int8 weights. Usage:

        ptq = PostTrainingQuantization(model)
        for batch in calib_loader: ptq.collect(batch)   # forward passes
        qmodel = ptq.convert(mode="dynamic_int8")

    Collection wraps each Linear-shaped layer with a recording hook; the
    calibrated scales are exposed in `ptq.scales` (layer name -> f32
    abs-max/127) for inspection, matching the reference's saved
    out_threshold attributes. Conversion reuses quantize_model's swap."""

    def __init__(self, model):
        self.model = model
        self.scales = {}
        self._hooks = []
        kinds = _linear_kinds()
        for name, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, kinds):
                self._hooks.append(sub.register_forward_pre_hook(
                    self._recorder(name)))

    def _recorder(self, name):
        def hook(layer, inputs):
            x = inputs[0]
            cur = float(jnp.max(jnp.abs(_arr(x)))) / 127.0
            prev = self.scales.get(name, 0.0)
            self.scales[name] = max(prev, cur)
            return None

        return hook

    def collect(self, *batch):
        """One calibration forward pass (eval mode, no grad). Per-sublayer
        training flags are snapshotted and restored — a blanket .train()
        would clobber deliberately frozen (eval) submodules."""
        from ..core.autograd import no_grad

        modes = [(sub, sub.training)
                 for _, sub in self.model.named_sublayers(include_self=True)]
        self.model.eval()
        try:
            with no_grad():
                self.model(*batch)
        finally:
            for sub, training in modes:
                sub.training = training

    def convert(self, mode="weight_only_int8"):
        """Remove the recording hooks and swap to int8 layers. For
        static_int8 the calibrated per-layer scales feed each
        QuantizedLinear's fixed activation grid."""
        for h in self._hooks:
            h.remove()
        self._hooks = []
        return quantize_model(self.model, mode, act_scales=self.scales)

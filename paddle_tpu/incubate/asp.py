"""ASP: automatic structured (n:m) sparsity.

Reference: python/paddle/fluid/contrib/sparsity/ + fleet asp_optimizer.py —
create 2:4 masks over FC/conv weights, prune, and re-apply masks after each
optimizer step so training stays on the sparse support. On TPU there is no
sparse-tensor-core datapath; the win is the same training recipe (masked
weights) with XLA folding the elementwise mask into the matmul epilogue.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer

_masks: Dict[int, np.ndarray] = {}  # id(param) -> mask


def calculate_density(x) -> float:
    arr = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
    return float((arr != 0).sum() / arr.size)


def check_sparsity(x, n=2, m=4) -> bool:
    """True if every group of m consecutive weights (last axis) has <= n nonzeros."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if arr.shape[-1] % m != 0:
        return False
    groups = arr.reshape(-1, m)
    return bool(((groups != 0).sum(1) <= n).all())


def create_mask(x, n=2, m=4) -> np.ndarray:
    """Keep the n largest-|w| entries in each group of m along the last axis
    (the reference's MaskAlgo_MASK_1D)."""
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    orig_shape = arr.shape
    assert orig_shape[-1] % m == 0, \
        f"last dim {orig_shape[-1]} not divisible by m={m}"
    groups = np.abs(arr.reshape(-1, m))
    keep = np.argsort(-groups, axis=1)[:, :n]
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, keep, 1.0, axis=1)
    return mask.reshape(orig_shape).astype(arr.dtype)


def _prunable_params(model: Layer):
    from ..nn.layers.common import Linear
    from ..nn.layers.conv_pool import _ConvNd

    for layer in model.sublayers(include_self=True):
        if isinstance(layer, (Linear, _ConvNd)):
            w = getattr(layer, "weight", None)
            if w is not None and w.ndim >= 2 and w.shape[-1] % 4 == 0:
                yield w


def prune_model(model: Layer, n=2, m=4, mask_algo="mask_1d",
                with_mask=True) -> Dict[str, float]:
    """Apply n:m masks to every FC/conv weight (reference sparsity.prune_model).
    Returns name->density after pruning. Masks are remembered so
    decorate()d optimizers re-apply them after each step."""
    import jax.numpy as jnp

    densities = {}
    for w in _prunable_params(model):
        mask = create_mask(w, n, m)
        _masks[id(w)] = mask
        w._data = w._data * jnp.asarray(mask)
        densities[w.name or str(id(w))] = calculate_density(w)
    return densities


def decorate(optimizer):
    """Wrap optimizer.step to re-apply the stored masks after the update
    (reference OptimizerWithSparsityGuarantee / asp_optimizer.py)."""
    import jax.numpy as jnp

    inner_step = optimizer.step

    def step():
        inner_step()
        for p in optimizer._parameter_list:
            mask = _masks.get(id(p))
            if mask is not None:
                p._data = p._data * jnp.asarray(mask)

    optimizer.step = step
    return optimizer


def reset_excluded_layers(model=None):
    _masks.clear()

"""Functional fused-transformer ops — `paddle.incubate.nn.functional`.

Reference: python/paddle/incubate/nn/functional/fused_transformer.py
(fused_feedforward:31, fused_multi_head_attention:215). The reference fuses
these into single CUDA ops (fused_feedforward_op, fused_attention_op); on
TPU the same fusion is XLA's job, so these are the mathematically identical
compositions the reference documents as pseudo code — under jit they fuse
into the same few kernels the CUDA ops hand-fuse. The Layer classes in
incubate.nn delegate to the same primitive ops.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from ..nn import functional as F
from ..ops import manipulation as P

__all__ = ["fused_feedforward", "fused_multi_head_attention"]


def _layer_norm(x, scale, bias, epsilon):
    dim = x.shape[-1]
    return F.layer_norm(x, normalized_shape=[dim], weight=scale, bias=bias,
                        epsilon=epsilon)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", name=None):
    """residual + dropout2(linear2(dropout1(act(linear1(ln(x)))))), with the
    layer_norm before (pre_layer_norm) or after the residual add
    (fused_transformer.py:31 pseudo code)."""
    residual = x
    if pre_layer_norm:
        x = _layer_norm(x, ln1_scale, ln1_bias, ln1_epsilon)
    act = getattr(F, activation)
    h = act(x @ linear1_weight if linear1_bias is None
            else x @ linear1_weight + linear1_bias)
    h = F.dropout(h, dropout1_rate, training=training, mode=mode)
    h = h @ linear2_weight if linear2_bias is None \
        else h @ linear2_weight + linear2_bias
    out = residual + F.dropout(h, dropout2_rate, training=training, mode=mode)
    if not pre_layer_norm:
        out = _layer_norm(out, ln2_scale, ln2_bias, ln2_epsilon)
    return out


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, name=None):
    """Self-attention block (fused_transformer.py:215 pseudo code).
    qkv_weight: [3, num_heads, head_dim, embed_dim] (the reference's fused
    layout); qkv_bias: [3, num_heads, head_dim]. ring_id != -1 (tensor-
    parallel allreduce inside the CUDA op) is out of scope here — under
    this framework mp runs through the mp_layers + GSPMD path."""
    if ring_id != -1:
        raise NotImplementedError(
            "ring_id is the reference CUDA op's in-kernel tensor-parallel "
            "allreduce; use distributed.meta_parallel mp_layers instead")
    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv decode belongs to the model-level KV-cache path "
            "(models.gpt.generate)")
    if mode != "upscale_in_train" and attn_dropout_rate:
        # scaled_dot_product_attention's internal weight-dropout has no mode
        # knob; silently diverging from the reference op's semantics would
        # be worse than refusing
        raise NotImplementedError(
            "attention-weight dropout only supports mode='upscale_in_train'")
    three, num_heads, head_dim, embed_dim = qkv_weight.shape
    assert three == 3
    b, s = x.shape[0], x.shape[1]
    residual = x
    if pre_layer_norm:
        x = _layer_norm(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    w = P.reshape(qkv_weight, (3 * num_heads * head_dim, embed_dim))
    qkv = x @ w.t()
    if qkv_bias is not None:
        qkv = qkv + P.reshape(qkv_bias, (3 * num_heads * head_dim,))
    qkv = P.reshape(qkv, (b, s, 3, num_heads, head_dim))
    q, k, v = P.unbind(qkv, axis=2)
    out = F.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate,
        is_causal=False, training=training)
    out = P.reshape(out, (b, s, num_heads * head_dim))
    out = out @ linear_weight
    if linear_bias is not None:
        out = out + linear_bias
    out = residual + F.dropout(out, dropout_rate, training=training,
                               mode=mode)
    if not pre_layer_norm:
        out = _layer_norm(out, ln_scale, ln_bias, ln_epsilon)
    return out

"""Segment reductions (reference python/paddle/incubate/tensor/math.py →
phi segment_pool kernels). TPU-native: jax.ops.segment_* lower to efficient
sorted-segment XLA scatters."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..ops._helpers import t_


def _segment(name, jfn, data, segment_ids, fill=0.0):
    data, segment_ids = t_(data), t_(segment_ids)

    def kernel(x, ids):
        n = int(jnp.max(ids)) + 1 if ids.size else 0
        return jfn(x, ids, num_segments=n)

    return apply(name, kernel, [data, segment_ids])


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    data, segment_ids = t_(data), t_(segment_ids)

    def kernel(x, ids):
        n = int(jnp.max(ids)) + 1 if ids.size else 0
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))

    return apply("segment_mean", kernel, [data, segment_ids])


def segment_max(data, segment_ids, name=None):
    def kernel(x, ids):
        n = int(jnp.max(ids)) + 1 if ids.size else 0
        out = jax.ops.segment_max(x, ids, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty segments -> 0

    return apply("segment_max", kernel, [t_(data), t_(segment_ids)])


def segment_min(data, segment_ids, name=None):
    def kernel(x, ids):
        n = int(jnp.max(ids)) + 1 if ids.size else 0
        out = jax.ops.segment_min(x, ids, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return apply("segment_min", kernel, [t_(data), t_(segment_ids)])

"""Functional quasi-Newton minimizers: BFGS and L-BFGS with strong-Wolfe
line search — `paddle.incubate.optimizer.functional`.

Reference: python/paddle/incubate/optimizer/functional/{bfgs,lbfgs,
line_search}.py (minimize_bfgs:23, minimize_lbfgs:23; Nocedal & Wright,
Numerical Optimization 2e, Algorithms 6.1 / 7.5 and 3.5-3.6). The reference
builds a static-graph while_loop op-by-op; here the whole minimization is
ONE `lax.while_loop` program — jittable, static shapes, one objective
value-and-grad evaluation per line-search step — so the entire solve
compiles to a single XLA computation (TPU-friendly: no host round-trips
between iterations).

Returns match the reference:
  minimize_bfgs  -> (is_converge, num_func_calls, position, f, g, H_inv)
  minimize_lbfgs -> (is_converge, num_func_calls, position, f, g)
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _wrap_objective(objective_func, dtype):
    """paddle-Tensor objective -> jax value_and_grad closure on raw arrays."""

    def f(x):
        out = objective_func(Tensor(x))
        val = out._data if isinstance(out, Tensor) else jnp.asarray(out)
        return val.astype(dtype).reshape(())

    return jax.value_and_grad(f)


def _strong_wolfe(value_and_grad, xk, pk, f0, dg0, alpha0, max_iters,
                  c1=1e-4, c2=0.9):
    """Strong-Wolfe line search (Nocedal Algorithms 3.5 bracket + 3.6 zoom)
    as one while_loop; exactly one objective evaluation per iteration.

    Returns (alpha, f_new, g_new, n_evals). alpha == 0 signals failure
    (caller treats it as converged/stuck, like the reference's
    line_search.py:263 fallback)."""
    dtype = f0.dtype

    def phi(alpha):
        return value_and_grad(xk + alpha * pk)

    # state: (i, phase, done, alpha_prev, f_prev,
    #         a_lo, f_lo, a_hi, alpha, f_alpha, g_alpha, n_evals)
    # phase 0 = bracketing with growing alpha; phase 1 = zoom bisection
    # (pure bisection: the bracket's f_hi / dg_lo are never consulted)
    def cond(s):
        i, phase, done = s[0], s[1], s[2]
        return (~done) & (i < max_iters)

    def body(s):
        (i, phase, done, a_prev, f_prev, a_lo, f_lo, a_hi,
         alpha, f_best, g_best, n_evals) = s
        # one evaluation per iteration, at the current trial point
        trial = jnp.where(phase == 0, alpha, 0.5 * (a_lo + a_hi))
        f_t, g_t = phi(trial)
        dg_t = g_t @ pk
        n_evals = n_evals + 1

        armijo_fail = (f_t > f0 + c1 * trial * dg0) | \
            ((i > 0) & (phase == 0) & (f_t >= f_prev))
        curvature_ok = jnp.abs(dg_t) <= -c2 * dg0

        # --- bracketing phase transitions -------------------------------
        # accept    : curvature holds and armijo holds
        b_accept = (phase == 0) & curvature_ok & ~armijo_fail
        # -> zoom(prev, trial): armijo failed (minimum bracketed)
        b_zoom_hi = (phase == 0) & armijo_fail
        # -> zoom(trial, prev): derivative turned non-negative
        b_zoom_lo = (phase == 0) & ~armijo_fail & ~curvature_ok & (dg_t >= 0)
        # else keep growing
        b_grow = (phase == 0) & ~(b_accept | b_zoom_hi | b_zoom_lo)

        # --- zoom phase transitions -------------------------------------
        z_shrink_hi = (phase == 1) & (armijo_fail | (f_t >= f_lo))
        z_accept = (phase == 1) & ~z_shrink_hi & curvature_ok
        z_flip = (phase == 1) & ~z_shrink_hi & ~curvature_ok & \
            (dg_t * (a_hi - a_lo) >= 0)

        new_phase = jnp.where(b_zoom_hi | b_zoom_lo, 1, phase)
        new_a_lo = jnp.where(
            b_zoom_hi, a_prev,
            jnp.where(b_zoom_lo, trial,
                      jnp.where((phase == 1) & ~z_shrink_hi, trial, a_lo)))
        new_f_lo = jnp.where(
            b_zoom_hi, f_prev,
            jnp.where(b_zoom_lo, f_t,
                      jnp.where((phase == 1) & ~z_shrink_hi, f_t, f_lo)))
        new_a_hi = jnp.where(
            b_zoom_hi, trial,
            jnp.where(b_zoom_lo, a_prev,
                      jnp.where(z_shrink_hi, trial,
                                jnp.where(z_flip, a_lo, a_hi))))

        accept = b_accept | z_accept
        new_alpha = jnp.where(accept, trial,
                              jnp.where(b_grow, 2.0 * alpha, alpha))
        f_best = jnp.where(accept, f_t, f_best)
        g_best = jnp.where(accept, g_t, g_best)

        return (i + 1, new_phase, done | accept, trial, f_t,
                new_a_lo, new_f_lo, new_a_hi,
                new_alpha, f_best, g_best, n_evals)

    zero = jnp.asarray(0.0, dtype)
    init = (jnp.int32(0), jnp.int32(0), jnp.asarray(False),
            zero, f0,                         # prev point = alpha 0
            zero, f0, zero,                   # lo/hi bracket
            jnp.asarray(alpha0, dtype), f0, jnp.zeros_like(xk),
            jnp.int32(0))
    out = jax.lax.while_loop(cond, body, init)
    done, alpha, f_best, g_best, n_evals = out[2], out[8], out[9], out[10], out[11]
    alpha = jnp.where(done, alpha, jnp.asarray(0.0, dtype))
    return alpha, f_best, g_best, n_evals


def _prep(initial_position, dtype, line_search_fn):
    if dtype not in ("float32", "float64"):
        raise ValueError(
            f"The dtype must be 'float32' or 'float64', but the specified "
            f"is {dtype}.")
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            f"Currently only support line_search_fn = 'strong_wolfe', but "
            f"the specified is '{line_search_fn}'")
    x0 = initial_position._data if isinstance(initial_position, Tensor) \
        else jnp.asarray(initial_position)
    return x0.astype(dtype)


def _check_h0(h0, dtype):
    """Validate + convert a user initial inverse-Hessian estimate. The BFGS
    update only preserves symmetry/positive-definiteness if H0 has them
    (reference bfgs.py raises the same way; a bad H0 here would otherwise
    end in a silent line-search failure at the initial point)."""
    import numpy as np

    H = (h0._data if isinstance(h0, Tensor) else jnp.asarray(h0)).astype(dtype)
    Hn = np.asarray(H)
    if not np.allclose(Hn, Hn.T, atol=1e-6):
        raise ValueError(
            "The initial_inverse_hessian_estimate should be symmetric")
    if np.linalg.eigvalsh(Hn).min() <= 0:
        raise ValueError(
            "The initial_inverse_hessian_estimate should be positive "
            "definite")
    return H


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    """BFGS minimization (reference bfgs.py:23; Nocedal Algorithm 6.1).
    Returns (is_converge, num_func_calls, position, objective_value,
    objective_gradient, inverse_hessian_estimate) as Tensors."""
    x0 = _prep(initial_position, dtype, line_search_fn)
    n = x0.shape[0]
    eye = jnp.eye(n, dtype=x0.dtype)
    if initial_inverse_hessian_estimate is None:
        H0 = eye
    else:
        H0 = _check_h0(initial_inverse_hessian_estimate, x0.dtype)

    vg = _wrap_objective(objective_func, x0.dtype)

    @jax.jit
    def solve(x0, H0):
        f0, g0 = vg(x0)

        def cond(s):
            k, done = s[0], s[1]
            return (~done) & (k < max_iters)

        def body(s):
            k, done, conv, n_calls, x, f, g, H = s
            p = -(H @ g)
            dg = g @ p
            alpha, f1, g1, evals = _strong_wolfe(
                vg, x, p, f, dg, initial_step_length,
                max_line_search_iters)
            n_calls = n_calls + evals
            sk = alpha * p
            x1 = x + sk
            yk = g1 - g
            rho_inv = yk @ sk
            rho = jnp.where(rho_inv == 0, 1000.0, 1.0 / rho_inv)
            V_t = eye - rho * jnp.outer(sk, yk)
            V = eye - rho * jnp.outer(yk, sk)
            H1 = V_t @ H @ V + rho * jnp.outer(sk, sk)
            # a failed line search (alpha == 0) keeps the old state
            ok = alpha != 0
            x1 = jnp.where(ok, x1, x)
            f1 = jnp.where(ok, f1, f)
            g1 = jnp.where(ok, g1, g)
            H1 = jnp.where(ok, H1, H)
            gnorm = jnp.max(jnp.abs(g1))
            pnorm = jnp.max(jnp.abs(p))
            conv = (gnorm < tolerance_grad) | (pnorm < tolerance_change)
            done = conv | ~ok
            return (k + 1, done, conv, n_calls, x1, f1, g1, H1)

        init = (jnp.int32(0), jnp.asarray(False), jnp.asarray(False),
                jnp.int32(1), x0, f0, g0, H0)
        k, done, conv, n_calls, x, f, g, H = jax.lax.while_loop(
            cond, body, init)
        return conv, n_calls, x, f, g, H

    conv, n_calls, x, f, g, H = solve(x0, H0)
    return (Tensor(conv), Tensor(n_calls), Tensor(x), Tensor(f), Tensor(g),
            Tensor(H))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8, tolerance_change=1e-8,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    """L-BFGS minimization (reference lbfgs.py:23; Nocedal Algorithm 7.5
    two-loop recursion over a circular (s, y) history). Returns
    (is_converge, num_func_calls, position, objective_value,
    objective_gradient)."""
    x0 = _prep(initial_position, dtype, line_search_fn)
    n = x0.shape[0]
    m = int(history_size)
    gamma0 = jnp.asarray(1.0, x0.dtype)
    # full-matrix H0 applied in the two-loop's center step r = H0 @ q (the
    # reference keeps the user matrix; gamma scaling only applies when no
    # H0 was given — an anisotropic preconditioner must not collapse to a
    # scalar)
    H0 = None
    if initial_inverse_hessian_estimate is not None:
        H0 = _check_h0(initial_inverse_hessian_estimate, x0.dtype)

    vg = _wrap_objective(objective_func, x0.dtype)

    @jax.jit
    def solve(x0):
        f0, g0 = vg(x0)
        S = jnp.zeros((m, n), x0.dtype)
        Y = jnp.zeros((m, n), x0.dtype)
        rho = jnp.zeros((m,), x0.dtype)

        def direction(g, S, Y, rho, gamma, count):
            """Two-loop recursion; history slots beyond `count` are no-ops."""
            cmin = jnp.minimum(count, m)
            valid = jnp.arange(m) < cmin

            def bwd(i, carry):
                q, a = carry
                j = (count - 1 - i) % m  # newest to oldest
                use = valid[i]
                ai = jnp.where(use, rho[j] * (S[j] @ q), 0.0)
                q = q - ai * Y[j]
                return q, a.at[j].set(ai)

            q, a = jax.lax.fori_loop(
                0, m, bwd, (g, jnp.zeros((m,), g.dtype)))
            r = (H0 @ q) if H0 is not None else gamma * q

            def fwd(i, r):
                j = (count - cmin + i) % m  # oldest to newest
                use = valid[i]
                bi = jnp.where(use, rho[j] * (Y[j] @ r), 0.0)
                return r + jnp.where(use, (a[j] - bi), 0.0) * S[j]

            return jax.lax.fori_loop(0, m, fwd, r)

        def cond(s):
            k, done = s[0], s[1]
            return (~done) & (k < max_iters)

        def body(s):
            k, done, conv, n_calls, x, f, g, S, Y, rho, gamma, count = s
            p = -direction(g, S, Y, rho, gamma, count)
            dg = g @ p
            alpha, f1, g1, evals = _strong_wolfe(
                vg, x, p, f, dg, initial_step_length,
                max_line_search_iters)
            n_calls = n_calls + evals
            sk = alpha * p
            yk = g1 - g
            sy = yk @ sk
            ok = (alpha != 0)
            store = ok & (sy > 1e-10)  # curvature guard keeps H psd
            slot = count % m
            S = jnp.where(store, S.at[slot].set(sk), S)
            Y = jnp.where(store, Y.at[slot].set(yk), Y)
            rho = jnp.where(store, rho.at[slot].set(1.0 / sy), rho)
            gamma = jnp.where(store, sy / (yk @ yk), gamma)
            count = count + jnp.where(store, 1, 0)
            x1 = jnp.where(ok, x + sk, x)
            f1 = jnp.where(ok, f1, f)
            g1 = jnp.where(ok, g1, g)
            gnorm = jnp.max(jnp.abs(g1))
            pnorm = jnp.max(jnp.abs(p))
            conv = (gnorm < tolerance_grad) | (pnorm < tolerance_change)
            done = conv | ~ok
            return (k + 1, done, conv, n_calls, x1, f1, g1, S, Y, rho,
                    gamma, count)

        init = (jnp.int32(0), jnp.asarray(False), jnp.asarray(False),
                jnp.int32(1), x0, f0, g0, S, Y, rho, gamma0, jnp.int32(0))
        out = jax.lax.while_loop(cond, body, init)
        return out[2], out[3], out[4], out[5], out[6]

    conv, n_calls, x, f, g = solve(x0)
    return Tensor(conv), Tensor(n_calls), Tensor(x), Tensor(f), Tensor(g)

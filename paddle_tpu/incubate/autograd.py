"""Functional autograd: vjp / jvp / Jacobian / Hessian.

Reference: python/paddle/incubate/autograd/ (primapi + functional.py) —
there these build on primitive ops with registered transpose rules. TPU-native
they ARE jax transforms: the user function (Tensor -> Tensor) is bridged to an
array function and handed to jax.vjp/jvp/jacfwd; our ops run under no_grad so
the eager tape stays out of the way and jax tracers flow straight through the
kernels."""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _array_fn(func, n_inputs):
    def f(*arrays):
        with no_grad():
            ins = [Tensor(a) for a in arrays]
            out = func(*ins)
        outs = _as_list(out)
        res = tuple(o._data if isinstance(o, Tensor) else o for o in outs)
        return res if len(res) > 1 else res[0]

    return f


def vjp(func: Callable, xs, v=None):
    """Vector-Jacobian product: returns (func(xs), vjp_result)."""
    xs_l = _as_list(xs)
    f = _array_fn(func, len(xs_l))
    out, vjp_fn = jax.vjp(f, *[x._data for x in xs_l])
    outs = _as_list(out)
    if v is None:
        cot = tuple(jnp.ones_like(o) for o in outs)
    else:
        cot = tuple(t._data for t in _as_list(v))
    grads = vjp_fn(cot if len(cot) > 1 else cot[0])
    wrap = lambda seq: [Tensor(g) for g in seq]
    out_t = [Tensor(o) for o in outs]
    g_t = wrap(grads)
    return (out_t if len(out_t) > 1 else out_t[0],
            g_t if len(g_t) > 1 else g_t[0])


def jvp(func: Callable, xs, v=None):
    """Jacobian-vector product: returns (func(xs), jvp_result)."""
    xs_l = _as_list(xs)
    f = _array_fn(func, len(xs_l))
    prim = [x._data for x in xs_l]
    if v is None:
        tang = [jnp.ones_like(p) for p in prim]
    else:
        tang = [t._data for t in _as_list(v)]
    out, jv = jax.jvp(f, tuple(prim), tuple(tang))
    outs, jvs = _as_list(out), _as_list(jv)
    out_t = [Tensor(o) for o in outs]
    jv_t = [Tensor(j) for j in jvs]
    return (out_t if len(out_t) > 1 else out_t[0],
            jv_t if len(jv_t) > 1 else jv_t[0])


class Jacobian:
    """Full Jacobian with lazy row access (reference autograd.Jacobian:
    J[i] rows, J[:] whole matrix; inputs/outputs flattened)."""

    def __init__(self, func, xs, is_batched=False):
        xs_l = _as_list(xs)
        f = _array_fn(func, len(xs_l))

        def flat_f(flat_in):
            # unflatten -> call -> flatten
            arrays, off = [], 0
            for x in xs_l:
                n = x._data.size
                arrays.append(flat_in[off:off + n].reshape(x._data.shape))
                off += n
            out = f(*arrays)
            outs = out if isinstance(out, tuple) else (out,)
            return jnp.concatenate([jnp.ravel(o) for o in outs])

        flat_in = jnp.concatenate([jnp.ravel(x._data) for x in xs_l])
        self._jac = jax.jacfwd(flat_f)(flat_in)

    @property
    def shape(self):
        return list(self._jac.shape)

    def __getitem__(self, idx):
        return Tensor(self._jac[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._jac)


class Hessian:
    """Hessian of a scalar function (reference autograd.Hessian)."""

    def __init__(self, func, xs, is_batched=False):
        xs_l = _as_list(xs)
        f = _array_fn(func, len(xs_l))

        def flat_f(flat_in):
            arrays, off = [], 0
            for x in xs_l:
                n = x._data.size
                arrays.append(flat_in[off:off + n].reshape(x._data.shape))
                off += n
            out = f(*arrays)
            out = out[0] if isinstance(out, tuple) else out
            return jnp.reshape(out, ())

        flat_in = jnp.concatenate([jnp.ravel(x._data) for x in xs_l])
        self._hess = jax.hessian(flat_f)(flat_in)

    @property
    def shape(self):
        return list(self._hess.shape)

    def __getitem__(self, idx):
        return Tensor(self._hess[idx])

    def numpy(self):
        import numpy as np

        return np.asarray(self._hess)

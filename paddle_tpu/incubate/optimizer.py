"""LookAhead + ModelAverage. Reference: python/paddle/incubate/optimizer/
lookahead.py and modelaverage.py."""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..core.autograd import no_grad
from ..core.tensor import Tensor


class LookAhead:
    """k steps forward, 1 step back (arXiv:1907.08610). Wraps an inner optimizer;
    every k steps the slow weights interpolate toward the fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._steps = 0
        self._slow: Dict[int, object] = {
            id(p): p._data for p in inner_optimizer._parameter_list}

    @property
    def _parameters(self):
        return self.inner_optimizer._parameter_list

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._steps += 1
        if self._steps % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._data - slow)
                self._slow[id(p)] = slow
                p._data = slow

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_steps"] = self._steps
        return sd

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Maintains a running average of parameters; apply()/restore() swaps the
    averaged weights in for evaluation (reference modelaverage.py with
    min/max_average_window semantics simplified to a cumulative mean)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000, name=None):
        assert parameters is not None, "ModelAverage needs the parameter list"
        self._parameters = list(parameters)
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._parameters}
        self._count = 0
        self._backup = None

    @no_grad()
    def step(self):
        for p in self._parameters:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        assert self._count > 0, "ModelAverage.step() never ran"
        self._backup = {id(p): p._data for p in self._parameters}
        for p in self._parameters:
            p._data = self._sum[id(p)] / self._count
        return _RestoreCtx(self) if need_restore else None

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._parameters:
                p._data = self._backup[id(p)]
            self._backup = None


class _RestoreCtx:
    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ma.restore()
        return False


# `paddle.incubate.optimizer.functional` submodule surface (reference
# python/paddle/incubate/optimizer/__init__.py:18): minimize_bfgs /
# minimize_lbfgs live in optimizer_functional.py; alias it so both
# attribute access and `import paddle_tpu.incubate.optimizer.functional`
# resolve even though `optimizer` is a module, not a package.
from . import optimizer_functional as functional  # noqa: E402,F401
import sys as _sys

_sys.modules[__name__ + ".functional"] = functional
del _sys

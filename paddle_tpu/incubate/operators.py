"""Incubate operators: fused softmax-mask + graph ops.

Reference: python/paddle/incubate/operators/ — softmax_mask_fuse(_upper_triangle)
(operators/fused/fused_softmax_mask*.cu), graph_send_recv
(operators/graph_send_recv_op.*), graph_reindex, graph_sample_neighbors,
graph_khop_sampler. On TPU the "fused" softmax-mask is one XLA fusion; the
message-passing op lowers to segment reductions; the samplers are host-side
(data preparation, like the reference's CPU kernels)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..ops._helpers import t_


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fusion (reference fused_softmax_mask_op.cu)."""

    def kernel(a, m):
        return jax.nn.softmax((a.astype(jnp.float32)
                               + m.astype(jnp.float32)), axis=-1).astype(a.dtype)

    return apply("softmax_mask_fuse", kernel, [t_(x), t_(mask)])


def softmax_mask_fuse_upper_triangle(x):
    """Causal-masked softmax without materializing the mask tensor
    (reference fused_softmax_mask_upper_triangle_op.cu)."""

    def kernel(a):
        s = a.shape[-1]
        causal = jnp.tril(jnp.ones((a.shape[-2], s), bool))
        scores = jnp.where(causal, a.astype(jnp.float32), -1e9)
        return jax.nn.softmax(scores, axis=-1).astype(a.dtype)

    return apply("softmax_mask_fuse_upper_triangle", kernel, [t_(x)])


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather rows at src_index, scatter-reduce onto dst_index (message
    passing; reference graph_send_recv_op)."""
    x, src_index, dst_index = t_(x), t_(src_index), t_(dst_index)

    def kernel(a, src, dst, pool_type, out_size):
        n = out_size or a.shape[0]
        msgs = a[src]
        if pool_type == "sum":
            return jax.ops.segment_sum(msgs, dst, num_segments=n)
        if pool_type == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            c = jax.ops.segment_sum(jnp.ones((msgs.shape[0],), a.dtype), dst,
                                    num_segments=n)
            return s / jnp.maximum(c, 1.0).reshape((-1,) + (1,) * (a.ndim - 1))
        if pool_type == "max":
            out = jax.ops.segment_max(msgs, dst, num_segments=n)
            return jnp.where(jnp.isfinite(out), out, 0.0)
        if pool_type == "min":
            out = jax.ops.segment_min(msgs, dst, num_segments=n)
            return jnp.where(jnp.isfinite(out), out, 0.0)
        raise ValueError(f"pool_type {pool_type!r}")

    return apply("graph_send_recv", kernel, [x, src_index, dst_index],
                 {"pool_type": pool_type, "out_size": out_size})


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a neighborhood subgraph to local ids (reference
    graph_reindex_op). Host-side: graph sampling is data prep."""
    x_np = np.asarray(t_(x)._data)
    nb_np = np.asarray(t_(neighbors)._data)
    cnt_np = np.asarray(t_(count)._data)
    keys = list(dict.fromkeys(x_np.tolist() + nb_np.tolist()))
    mapping = {k: i for i, k in enumerate(keys)}
    reindex_src = np.array([mapping[v] for v in nb_np], np.int64)
    # dst: each center i repeated count[i] times
    reindex_dst = np.repeat(np.arange(len(x_np), dtype=np.int64), cnt_np)
    out_nodes = np.array(keys, np.int64)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1, return_eids=False,
                           flag_perm_buffer=False, name=None):
    """Uniformly sample up to sample_size neighbors per input node from a CSC
    graph (reference graph_sample_neighbors_op). Host-side."""
    row_np = np.asarray(t_(row)._data)
    colptr_np = np.asarray(t_(colptr)._data)
    nodes_np = np.asarray(t_(input_nodes)._data)
    rng = np.random.default_rng()
    out_neighbors, out_count = [], []
    for n in nodes_np:
        beg, end = int(colptr_np[n]), int(colptr_np[n + 1])
        neigh = row_np[beg:end]
        if sample_size > 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        out_neighbors.append(neigh)
        out_count.append(len(neigh))
    neighbors = np.concatenate(out_neighbors) if out_neighbors else \
        np.zeros((0,), row_np.dtype)
    return (Tensor(jnp.asarray(neighbors)),
            Tensor(jnp.asarray(np.array(out_count, np.int64))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighborhood sampling + reindex (reference
    graph_khop_sampler_op). Host-side."""
    frontier = np.asarray(t_(input_nodes)._data)
    all_src, all_dst = [], []
    seen = list(dict.fromkeys(frontier.tolist()))
    cur = frontier
    for k in sample_sizes:
        neigh, cnt = graph_sample_neighbors(row, colptr,
                                            Tensor(jnp.asarray(cur)),
                                            sample_size=k)
        neigh_np = np.asarray(neigh._data)
        cnt_np = np.asarray(cnt._data)
        dst = np.repeat(cur, cnt_np)
        all_src.append(neigh_np)
        all_dst.append(dst)
        nxt = [v for v in neigh_np.tolist() if v not in set(seen)]
        seen.extend(dict.fromkeys(nxt))
        cur = np.array(list(dict.fromkeys(neigh_np.tolist())), frontier.dtype)
    src = np.concatenate(all_src) if all_src else np.zeros((0,), np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros((0,), np.int64)
    mapping = {k: i for i, k in enumerate(seen)}
    reindex_src = np.array([mapping[v] for v in src], np.int64)
    reindex_dst = np.array([mapping[v] for v in dst], np.int64)
    return (Tensor(jnp.asarray(np.array(seen, np.int64))),
            Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)))

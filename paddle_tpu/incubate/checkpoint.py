"""Auto checkpoint: epoch-range resume hooks.

Reference: python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py —
train_epoch_range wraps the epoch loop, snapshots program+scope per epoch
under a job id, and on restart fast-forwards to the first unfinished epoch.
TPU-native: the snapshot is the model/optimizer state_dicts via paddle.save;
job identity comes from PADDLE_JOB_ID (the launcher sets it)."""
from __future__ import annotations

import json
import os
from typing import Optional


class _EpochRange:
    def __init__(self, max_epoch_num: int, save_dir: Optional[str] = None,
                 name: Optional[str] = None):
        self.max_epoch_num = max_epoch_num
        job = name or os.environ.get("PADDLE_JOB_ID", "default")
        root = save_dir or os.environ.get("PADDLE_CHECKPOINT_DIR",
                                          os.path.join(".", "auto_checkpoint"))
        self.dir = os.path.join(root, job)
        os.makedirs(self.dir, exist_ok=True)
        self._meta_path = os.path.join(self.dir, "meta.json")
        self._start = 0
        self._bound = []  # (name, obj) pairs to snapshot
        if os.path.exists(self._meta_path):
            with open(self._meta_path) as f:
                meta = json.load(f)
            self._start = int(meta.get("next_epoch", 0))

    def bind(self, **named_objects):
        """Register model/optimizer (anything with state_dict/set_state_dict)."""
        self._bound = list(named_objects.items())
        # restore on resume
        from .. import load

        for name, obj in self._bound:
            path = os.path.join(self.dir, f"{name}.pdparams")
            if os.path.exists(path) and self._start > 0:
                obj.set_state_dict(load(path))
        return self

    def __iter__(self):
        from .. import save

        for epoch in range(self._start, self.max_epoch_num):
            yield epoch
            for name, obj in self._bound:
                save(obj.state_dict(), os.path.join(self.dir, f"{name}.pdparams"))
            with open(self._meta_path, "w") as f:
                json.dump({"next_epoch": epoch + 1}, f)


def train_epoch_range(max_epoch_num: int, save_checkpoint_inter: int = 0,
                      save_dir: Optional[str] = None, name: Optional[str] = None):
    """`for epoch in train_epoch_range(N): ...` resumes after restart.
    Call .bind(model=m, optimizer=o) on the returned range to checkpoint
    state each epoch (reference acp.train_epoch_range)."""
    return _EpochRange(max_epoch_num, save_dir, name)

"""Gradient clipping. Reference: python/paddle/fluid/clip.py (ClipGradByValue/Norm/GlobalNorm).
Under hybrid parallelism the global norm must be allreduced across mp/pp groups — the
distributed HybridParallelOptimizer wraps this (see distributed/fleet, reference
hybrid_parallel_optimizer.py:51)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor


class ClipGradBase:
    def _clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        with no_grad():
            return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def compute_global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g._data.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return None
        total = sq[0]
        for s in sq[1:]:
            total = total + s
        return jnp.sqrt(total)

    def _clip(self, params_grads):
        gn = self.compute_global_norm([g for _, g in params_grads])
        if gn is None:
            return params_grads
        scale = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    params = [p for p in parameters if p.grad is not None]
    clip = ClipGradByGlobalNorm(max_norm)
    pg = clip([(p, p.grad) for p in params])
    for p, g in pg:
        p.grad = g
    return clip.compute_global_norm([g for _, g in pg])

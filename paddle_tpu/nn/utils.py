"""paddle.nn.utils — weight/spectral norm reparametrization and
parameter<->vector transforms.

Reference: python/paddle/nn/utils/{weight_norm_hook.py:155,
spectral_norm_hook.py, transform_parameters.py:73,121}. TPU-native design
delta: instead of a forward pre-hook that caches a recomputed weight (which
would be a CONSTANT to any trace taken later — silently stopping gradients
under jit/to_static), the weight becomes an instance-class PROPERTY computed
from the g/v (or orig) Parameters at every access. Whoever reads
`layer.weight` — the eager tape, functional_call inside pjit, or a
to_static trace — sees an expression of the live Parameters, so gradients
always flow and no trace-time Tensor is ever stored on the layer.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops import manipulation as P
from .layer import Parameter

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]

_EPS = 1e-12


def _check_dim(w, dim, what):
    ndim = len(w.shape)
    if not (-1 <= dim < ndim):
        raise ValueError(
            f"{what}: dim must be -1 (whole-tensor) or in [0, {ndim}) for a "
            f"{ndim}-D weight, got {dim}")


def _norm_except_dim(v, dim):
    """L2 norm over all axes except `dim` (reference norm_except_dim:45);
    dim == -1 -> one global norm."""
    a = v._data if isinstance(v, Tensor) else jnp.asarray(v)
    if dim == -1:
        return Tensor(jnp.sqrt(jnp.sum(a * a) + _EPS))
    axes = tuple(i for i in range(a.ndim) if i != dim)
    return Tensor(jnp.sqrt(jnp.sum(a * a, axis=axes) + _EPS))


def _weight_from_gv(g, v, dim):
    """w = g * v / ||v||, broadcasting g over every axis but `dim`
    (reference _weight_norm:64). Built from Tensor ops so autograd records
    the reparametrization and gradients reach g AND v."""
    ndim = len(v.shape)
    if dim == -1:
        norm = ((v * v).sum() + _EPS).sqrt()
        return g * v / norm
    axes = [i for i in range(ndim) if i != dim]
    norm = ((v * v).sum(axis=axes, keepdim=True) + _EPS).sqrt()
    shape = [1] * ndim
    shape[dim] = v.shape[dim]
    return P.reshape(g, shape) * v / norm


def _install_property(layer, name, fget):
    """Swap the instance onto a per-instance subclass carrying `name` as a
    property. The previous class is recorded so removal can restore it."""
    prev_cls = layer.__class__
    new_cls = type(f"{prev_cls.__name__}", (prev_cls,), {name: property(fget)})
    layer.__class__ = new_cls
    return prev_cls


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize `layer.<name>` as magnitude g and direction v
    (arXiv:1602.07868; reference weight_norm_hook.py:155): the original
    Parameter is replaced by `<name>_g` / `<name>_v`, and `<name>` becomes
    a property recomputing g * v/||v|| from the live Parameters at every
    access (gradients flow on the eager tape AND inside traces)."""
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"layer has no Parameter {name!r}")
    if hasattr(layer, f"_{name}_weight_norm"):
        raise ValueError(f"weight_norm already applied to {name!r}")
    _check_dim(w, dim, "weight_norm")

    g = Parameter(_norm_except_dim(w, dim)._data)
    v = Parameter(w._data)
    del layer._parameters[name]
    setattr(layer, f"{name}_g", g)
    setattr(layer, f"{name}_v", v)

    def fget(self):
        return _weight_from_gv(getattr(self, f"{name}_g"),
                               getattr(self, f"{name}_v"), dim)

    prev_cls = _install_property(layer, name, fget)
    layer.__dict__[f"_{name}_weight_norm"] = (prev_cls, dim)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Bake the current g/v back into a single `<name>` Parameter and drop
    the property (reference weight_norm_hook.py:202)."""
    key = f"_{name}_weight_norm"
    if key not in layer.__dict__:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    prev_cls, dim = layer.__dict__.pop(key)
    w = _weight_from_gv(getattr(layer, f"{name}_g"),
                        getattr(layer, f"{name}_v"), dim)
    del layer._parameters[f"{name}_g"]
    del layer._parameters[f"{name}_v"]
    layer.__class__ = prev_cls
    setattr(layer, name, Parameter(w._data))
    return layer


def _default_sn_dim(layer):
    """Reference spectral_norm_hook default: dim=None auto-selects 1 for
    layers whose weight stores the output on axis 1 (Linear [in, out] and
    transposed convs), else 0."""
    cls = type(layer).__name__
    return 1 if ("Linear" in cls or "Transpose" in cls) else 0


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide `layer.<name>` by its largest singular value sigma, with u
    estimated by power iteration (reference spectral_norm_hook.py). The
    original Parameter moves to `<name>_orig`; `<name>` becomes a property
    computing W / sigma where sigma = u^T W v is a live expression of W
    (u, v detached, the standard SN-GAN treatment) — so gradients flow in
    eager and traced contexts alike. The u buffer advances one power
    iteration per EAGER access; inside a trace it stays frozen."""
    if n_power_iterations < 1:
        raise ValueError("n_power_iterations must be >= 1")
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"layer has no Parameter {name!r}")
    if dim is None:
        dim = _default_sn_dim(layer)
    _check_dim(w, dim, "spectral_norm")
    wa = w._data
    ndim = wa.ndim
    h = wa.shape[dim]

    import jax

    from ..core import random as random_mod

    u0 = jax.random.normal(random_mod.next_key(), (h,), jnp.float32)
    layer.register_buffer(f"{name}_u", Tensor(u0 / (jnp.linalg.norm(u0)
                                                    + eps)))

    orig_name = f"{name}_orig"
    del layer._parameters[name]
    setattr(layer, orig_name, w)
    perm = [dim] + [i for i in range(ndim) if i != dim]

    def fget(self):
        import jax

        from ..jit import in_jit_trace

        w_t = getattr(self, orig_name)
        m_t = P.reshape(P.transpose(w_t, perm), (h, -1))
        u = getattr(self, f"{name}_u")._data
        m = jax.lax.stop_gradient(m_t._data)
        for _ in range(n_power_iterations):
            vvec = m.T @ u
            vvec = vvec / (jnp.linalg.norm(vvec) + eps)
            u = m @ vvec
            u = u / (jnp.linalg.norm(u) + eps)
        if not in_jit_trace():
            getattr(self, f"{name}_u")._data = u  # persist eager PI progress
        # sigma = u^T W v via Tensor ops: a live function of W
        sigma = (Tensor(u) * (m_t @ Tensor(vvec))).sum()
        return w_t / sigma

    _install_property(layer, name, fget)
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten + concat parameters into ONE 1-D Tensor (reference
    transform_parameters.py:73)."""
    parts = [P.reshape(p, (-1,)) for p in parameters]
    return P.concat(parts, axis=0)


def vector_to_parameters(vec, parameters, name=None):
    """Slice a flat vector back into the parameters, in place (reference
    transform_parameters.py:121). Accepts any iterable."""
    parameters = list(parameters)
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    total = sum(int(np.prod(p.shape)) if p.shape else 1 for p in parameters)
    if total != data.shape[0]:
        raise ValueError(
            f"vector length {data.shape[0]} does not match total parameter "
            f"size {total}")
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p.set_value(data[off:off + n].reshape(p.shape))
        off += n
    return parameters

"""paddle.nn equivalent."""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, ParamAttr, Parameter  # noqa: F401
from .layers.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, SELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, RReLU,
    Sigmoid, Silu, SiLU, Softmax, Softmax2D, Softplus, Softshrink, Softsign,
    Swish, Tanh, Tanhshrink, ThresholdedReLU,
)
from .layers.common import (  # noqa: F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D, Dropout3D,
    Embedding, Flatten, Fold, Identity, Linear, Pad1D, Pad2D, Pad3D,
    PairwiseDistance, PixelShuffle, SpectralNorm, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layers.container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layers.conv_pool import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
    MaxPool1D, MaxPool2D, MaxPool3D, MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
)
from .layers.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss, CTCLoss,
    HingeEmbeddingLoss, HSigmoidLoss, KLDivLoss, L1Loss, MarginRankingLoss,
    MSELoss, NLLLoss, SmoothL1Loss,
)
from .layers.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm1D,
    InstanceNorm2D, InstanceNorm3D, LayerNorm, LocalResponseNorm, RMSNorm,
    SyncBatchNorm,
)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)
from .layers.rnn import (  # noqa: F401
    GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .layers.decode import BeamSearchDecoder, Decoder, dynamic_decode, gather_tree  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

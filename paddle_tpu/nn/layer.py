"""nn.Layer base + Parameter + ParamAttr.

Reference parity: python/paddle/fluid/dygraph/layers.py (Layer), framework.py ParamBase/EagerParamBase.
Layers hold eager Tensors; `paddle_tpu.jit.functional_call` temporarily swaps them for traced
arrays so the same Layer definitions run inside pjit — the bridge to distributed execution.
"""
from __future__ import annotations

import collections
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core.tensor import Tensor


class Parameter(Tensor):
    """A trainable Tensor (stop_gradient=False). Analogue of EagerParamBase."""

    def __init__(self, data, trainable: bool = True, name: str = ""):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.is_distributed = False
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.dist_attr = None  # PartitionSpec-like sharding annotation (TP/sharding)

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _param_flatten(p: Parameter):
    return (p._data,), (p._stop_gradient, p.name)


def _param_unflatten(aux, children):
    (data,) = children
    sg, name = aux
    out = Parameter(data, trainable=not sg, name=name)
    return out


jax.tree_util.register_pytree_node(Parameter, _param_flatten, _param_unflatten)


class ParamAttr:
    """Reference: python/paddle/fluid/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if attr is False:
            return False
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # bare initializer
        return ParamAttr(initializer=attr)


def create_parameter(shape, dtype="float32", name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone parameter factory (reference: paddle.create_parameter,
    python/paddle/tensor/creation.py)."""
    from . import initializer as I

    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    if name is not None and attr.name is None:
        attr.name = name
    dtype = dtypes.convert_dtype(dtype)
    init = attr.initializer or default_initializer
    if init is None:
        init = I._global_default(is_bias)  # set_global_initializer override
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    data = init(tuple(int(s) for s in shape), dtype)
    p = Parameter(data, trainable=attr.trainable, name=attr.name or "")
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    return p


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # ---- attribute plumbing ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
            if buffers is not None:
                buffers.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    raise TypeError(
                        f"cannot assign non-Parameter to parameter attribute {name!r}")
            if layers is not None and name in layers and value is None:
                del layers[name]
                return
            if buffers is not None and name in buffers:
                if value is None:
                    del buffers[name]
                elif isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- parameter creation ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from . import initializer as I

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype or self._dtype)
        init = attr.initializer or default_initializer
        if init is None:
            init = I._global_default(is_bias)  # set_global_initializer override
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, trainable=attr.trainable, name=attr.name or "")
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        t = Tensor(jnp.zeros((), dtypes.convert_dtype(dtype or self._dtype)))
        if persistable:
            t.persistable = True
        return t

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor))
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- traversal ----
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + ("." if name else "") + pname, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + ("." if name else "") + bname, b)

    def _traverse(self, prefix="", include_sublayers=True):
        yield prefix, self
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = prefix + ("." if prefix else "") + lname
                yield from sub._traverse(sub_prefix, True)

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for _, sub in self._sub_layers.items():
            if sub is not None:
                out.extend(sub.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for lname, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = prefix + ("." if prefix else "") + lname
            yield from sub.named_sublayers(p, include_self=True)

    # ---- mode / apply / moving ----
    def train(self):
        self.training = True
        for sub in self.children():
            sub.train()
        return self

    def eval(self):
        self.training = False
        for sub in self.children():
            sub.eval()
        return self

    def apply(self, fn):
        for sub in self.children():
            sub.apply(fn)
        fn(self)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        def _move(t):
            if t is None:
                return
            new = t.to(device=device, dtype=dtype)
            t._data = new._data

        for _, p in self.named_parameters():
            _move(p)
        for _, b in self.named_buffers():
            _move(b)
        if dtype is not None:
            self._dtype = dtypes.convert_dtype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, use_hook=True,
                   structured_name_prefix="", include_non_persistable_buffer=False):
        dest = collections.OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for prefix, layer in self._traverse(structured_name_prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None:
                    continue
                if (not include_non_persistable_buffer
                        and bname in layer._non_persistable_buffer_names):
                    continue
                dest[prefix + ("." if prefix else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                data = v._data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                own[k].set_value(data)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ----
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{self.__class__.__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def full_name(self):
        return self._name_scope

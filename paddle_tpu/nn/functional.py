"""paddle.nn.functional surface: re-export of the functional op library."""
from ..ops.activation import *  # noqa: F401,F403
from ..ops.nn_functional import *  # noqa: F401,F403
from ..ops.manipulation import pad  # noqa: F401
from .layers.decode import gather_tree  # noqa: F401
from ..ops.creation import diag  # noqa: F401

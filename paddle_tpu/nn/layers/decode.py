"""Seq2seq decoding: Decoder protocol, BeamSearchDecoder, dynamic_decode.

Reference parity: python/paddle/fluid/layers/rnn.py (Decoder :700, BeamSearchDecoder
:850 — beam expansion/tile, log-prob accumulation, topk over beam*vocab, parent
gathering — and dynamic_decode :1260) plus `gather_tree` (paddle/fluid/operators/
gather_tree_op.cc) for beam reconstruction.

Decoding is inherently data-dependent, so like the reference's dygraph path this runs
a host-side step loop over jitted step computations; each step's compute (cell + topk
+ gathers) is still XLA-compiled.
"""
from __future__ import annotations

import collections

import numpy as np

from ...core.tensor import Tensor
from ...core import dtype as dtypes
from ..layer import Layer
from ...ops import creation as C
from ...ops import manipulation as P
from ...ops import math as M
from ...ops import reduction as R
from ...ops import activation as A

import jax.numpy as jnp


class Decoder:
    """Abstract decode protocol (reference rnn.py:Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a cell's token distribution (reference rnn.py:850)."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # ---- beam shape helpers (reference: _expand_to_beam_size/_merge/_split) ----
    def _expand_to_beam_size(self, x):
        x = P.unsqueeze(x, 1)
        tile = [1] * len(x.shape)
        tile[1] = self.beam_size
        return P.tile(x, tile)

    def _merge_batch_beams(self, x):
        return P.reshape(x, [-1] + list(x.shape[2:]))

    def _split_batch_beams(self, x):
        return P.reshape(x, [-1, self.beam_size] + list(x.shape[1:]))

    def _map_states(self, states, fn):
        if isinstance(states, (tuple, list)):
            return tuple(self._map_states(s, fn) for s in states)
        return fn(states)

    def initialize(self, initial_cell_states):
        batch = (initial_cell_states[0] if isinstance(initial_cell_states,
                 (tuple, list)) else initial_cell_states).shape[0]
        self.batch_size = batch
        cell_states = self._map_states(
            initial_cell_states,
            lambda s: self._merge_batch_beams(self._expand_to_beam_size(s)))
        # log_probs: beam 0 live, the rest -inf so step 1 expands from beam 0 only
        lp_row = np.full((self.beam_size,), -1e9, np.float32)
        lp_row[0] = 0.0
        log_probs = Tensor(jnp.asarray(np.tile(lp_row, (batch, 1))))
        finished = Tensor(jnp.zeros((batch, self.beam_size), jnp.bool_))
        lengths = Tensor(jnp.zeros((batch, self.beam_size), jnp.int64))
        init_ids = C.full([batch, self.beam_size], self.start_token, "int64")
        init_inputs = (self.embedding_fn(init_ids) if self.embedding_fn
                       else init_ids)
        return (init_inputs,
                self.StateWrapper(cell_states, log_probs, finished, lengths),
                finished)

    def step(self, time, inputs, states, **kwargs):
        merged_inputs = self._merge_batch_beams(inputs)
        cell_out, next_cell_states = self.cell(
            merged_inputs, states.cell_states, **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        vocab = cell_out.shape[-1]
        step_log_probs = A.log_softmax(self._split_batch_beams(cell_out))  # [N,B,V]
        # finished beams only extend with end_token (log-prob 0), everything else -inf
        fin = states.finished.astype("float32").unsqueeze(-1)
        onehot_end = Tensor(jnp.asarray(
            np.where(np.arange(vocab) == self.end_token, 0.0, -1e9)
            .astype(np.float32)))
        step_log_probs = step_log_probs * (1.0 - fin) + fin * onehot_end
        total = states.log_probs.unsqueeze(-1) + step_log_probs  # [N,B,V]
        flat = P.reshape(total, [-1, self.beam_size * vocab])
        topk_scores, topk_idx = P.topk(flat, self.beam_size)  # [N,B]
        parent = P.cast(M.floor_divide(topk_idx, vocab), "int64")
        token = M.remainder(topk_idx, vocab)

        # gather beam-indexed state by parent
        gather_idx = parent + Tensor(jnp.arange(self.batch_size)[:, None]) * self.beam_size
        flat_gather = P.reshape(gather_idx, [-1])

        def regather(s):
            return P.index_select(s, flat_gather, axis=0)

        next_cell_states = self._map_states(next_cell_states, regather)
        next_finished = P.reshape(
            P.index_select(P.reshape(states.finished, [-1]), flat_gather),
            [self.batch_size, self.beam_size])
        next_lengths = P.reshape(
            P.index_select(P.reshape(states.lengths, [-1]), flat_gather),
            [self.batch_size, self.beam_size])
        next_lengths = next_lengths + P.cast(
            M.logical_not(next_finished), "int64")
        next_finished = M.logical_or(
            next_finished, M.equal(token, C.full([1], self.end_token, "int64")))

        next_state = self.StateWrapper(next_cell_states, topk_scores,
                                       next_finished, next_lengths)
        output = self.OutputWrapper(topk_scores, token, parent)
        next_inputs = (self.embedding_fn(token) if self.embedding_fn else token)
        return output, next_state, next_inputs, next_finished

    def finalize(self, outputs, final_states, sequence_lengths):
        predicted = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return predicted, final_states

    @property
    def tracks_own_finished(self):
        return True


def gather_tree(ids, parents):
    """Reconstruct full beams from per-step tokens + parent pointers
    (reference: gather_tree_op; ids/parents are [T, N, beam])."""
    ids_np = np.asarray(ids._data if isinstance(ids, Tensor) else ids)
    par_np = np.asarray(parents._data if isinstance(parents, Tensor) else parents)
    T, N, B = ids_np.shape
    out = np.zeros_like(ids_np)
    for n in range(N):
        for b in range(B):
            beam = b
            for t in range(T - 1, -1, -1):
                out[t, n, b] = ids_np[t, n, beam]
                beam = par_np[t, n, beam]
    return Tensor(jnp.asarray(out))


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run `decoder` until every sequence finishes or max_step_num
    (reference rnn.py:1260)."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs_acc = []
    step = 0
    while True:
        if max_step_num is not None and step >= max_step_num:
            break
        if bool(np.asarray(finished._data).all()):
            break
        outputs, next_states, next_inputs, next_finished = decoder.step(
            step, inputs, states, **kwargs)
        if not decoder.tracks_own_finished:
            next_finished = M.logical_or(next_finished, finished)
        step_outputs_acc.append(outputs)
        inputs, states, finished = next_inputs, next_states, next_finished
        step += 1

    if not step_outputs_acc:
        raise ValueError("dynamic_decode ran zero steps; check initial finished state")

    # stack along time (time-major first, like the reference)
    first = step_outputs_acc[0]
    if isinstance(first, tuple) and hasattr(first, "_fields"):
        stacked = type(first)(*[
            P.stack([getattr(o, f) for o in step_outputs_acc], axis=0)
            for f in first._fields])
    else:
        stacked = P.stack(step_outputs_acc, axis=0)

    final_outputs, final_states = decoder.finalize(
        stacked, states, getattr(states, "lengths", None))
    if not output_time_major:
        def to_batch_major(x):
            if isinstance(x, Tensor):
                perm = [1, 0] + list(range(2, len(x.shape)))
                return P.transpose(x, perm)
            return x
        if isinstance(final_outputs, tuple) and hasattr(final_outputs, "_fields"):
            final_outputs = type(final_outputs)(
                *[to_batch_major(getattr(final_outputs, f))
                  for f in final_outputs._fields])
        else:
            final_outputs = to_batch_major(final_outputs)
    if return_length:
        return final_outputs, final_states, getattr(states, "lengths", None)
    return final_outputs, final_states

"""Activation layers. Reference: python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from ...ops import activation as A
from .. import initializer as I
from ..layer import Layer


def _simple(name, fn, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            # capture common scalar args by position/name
            sig_names = list(kwargs.keys())
            self._args = args
            self._kwargs.update({k: v for k, v in kwargs.items() if k != "name"})

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    return _Act


ReLU = _simple("ReLU", A.relu)
ReLU6 = _simple("ReLU6", A.relu6)
Sigmoid = _simple("Sigmoid", A.sigmoid)
Tanh = _simple("Tanh", A.tanh)
SiLU = _simple("SiLU", A.silu)
Swish = _simple("Swish", A.swish)
Mish = _simple("Mish", A.mish)
Hardswish = _simple("Hardswish", A.hardswish)
Hardsigmoid = _simple("Hardsigmoid", A.hardsigmoid)
Softsign = _simple("Softsign", A.softsign)
Tanhshrink = _simple("Tanhshrink", A.tanhshrink)
LogSigmoid = _simple("LogSigmoid", A.log_sigmoid)
GELU = _simple("GELU", A.gelu)
ELU = _simple("ELU", A.elu)
SELU = _simple("SELU", A.selu)
CELU = _simple("CELU", A.celu)
LeakyReLU = _simple("LeakyReLU", A.leaky_relu)
Hardtanh = _simple("Hardtanh", A.hardtanh)
Hardshrink = _simple("Hardshrink", A.hardshrink)
Softshrink = _simple("Softshrink", A.softshrink)
Softplus = _simple("Softplus", A.softplus)
ThresholdedReLU = _simple("ThresholdedReLU", A.thresholded_relu)
Maxout = _simple("Maxout", A.maxout)
GLU = _simple("GLU", A.glu)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return A.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return A.log_softmax(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr, default_initializer=I.Constant(init))

    def forward(self, x):
        return A.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return A.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW/CHW inputs (reference nn.Softmax2D)."""

    def forward(self, x):
        assert x.ndim in (3, 4), "Softmax2D expects CHW or NCHW input"
        return A.softmax(x, axis=-3)


Silu = SiLU  # reference exports both spellings

"""Normalization layers. Reference: python/paddle/nn/layer/norm.py."""
from __future__ import annotations

from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        from ...core.tensor import Tensor

        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format, use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else "NLC", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NDHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU inside pjit, batch stats are computed over the global batch automatically
    when the batch axis is sharded (XLA lowers the mean/var reduce to an allreduce over
    the mesh) — the reference's separate sync_batch_norm op (c_sync_calc + nccl allreduce,
    paddle/fluid/operators/sync_batch_norm_op.cu) is unnecessary. Eagerly on one chip it
    equals BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            out.weight.set_value(layer.weight)
            out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={list(self._normalized_shape)}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """TPU extra (used by the GPT/LLM model zoo)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr, default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_channels,), attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias, self._epsilon,
                            self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None if weight_attr is False else self.create_parameter(
                (num_features,), default_initializer=I.Constant(1.0))
            self.bias = None if bias_attr is False else self.create_parameter(
                (num_features,), is_bias=True)
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)

"""Common layers. Reference: python/paddle/nn/layer/common.py."""
from __future__ import annotations

import math

import numpy as np

from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer, ParamAttr


class Linear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False,
                 weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierNormal())
        if padding_idx is not None:
            import jax.numpy as jnp

            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops import manipulation as P

        return P.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False,
                 align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        from ...ops import manipulation as P

        return P.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    pass


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr,
            default_initializer=I.Uniform(-1.0 / math.sqrt(in1_features),
                                          1.0 / math.sqrt(in1_features)))
        self.bias = self.create_parameter((1, out_features), attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        from ...ops import linalg as L

        out = L.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = out + self.bias
        return out


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes, self.kernel_sizes = output_sizes, kernel_sizes
        self.strides, self.paddings, self.dilations = strides, paddings, dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ...ops import linalg as L

        diff = x - y + self.epsilon
        return L.norm(diff, p=self.p, axis=-1, keepdim=self.keepdim)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a given weight tensor
    (reference nn.SpectralNorm / spectral_norm op)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            (h,), dtype=dtype, default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            (w,), dtype=dtype, default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, x):
        from ...core.autograd import no_grad
        from ...ops import linalg as L

        dims = list(range(x.ndim))
        perm = [self.dim] + [d for d in dims if d != self.dim]
        mat = x.transpose(perm).reshape([x.shape[self.dim], -1])
        u, v = self.weight_u, self.weight_v
        with no_grad():
            for _ in range(self.power_iters):
                v_new = mat.t().matmul(u.reshape([-1, 1])).reshape([-1])
                v = v_new / (L.norm(v_new) + self.eps)
                u_new = mat.matmul(v.reshape([-1, 1])).reshape([-1])
                u = u_new / (L.norm(u_new) + self.eps)
            self.weight_u.set_value(u._data)
            self.weight_v.set_value(v._data)
        sigma = u.reshape([1, -1]).matmul(mat).matmul(v.reshape([-1, 1]))
        return x / sigma.reshape([1] * x.ndim)

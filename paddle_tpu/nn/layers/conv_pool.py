"""Conv + pooling layers. Reference: python/paddle/nn/layer/conv.py, pooling.py.
Weight layout matches the reference ([out_c, in_c//groups, *k]; transpose: [in_c, out_c//groups, *k])."""
from __future__ import annotations

import numpy as np

from ...ops import nn_functional as F
from .. import initializer as I
from ..layer import Layer


def _ntuple(v, n):
    if isinstance(v, (int, float)):
        return (int(v),) * n
    v = tuple(int(x) for x in v)
    return v * n if len(v) == 1 else v


class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format=None, transpose=False, output_padding=0):
        super().__init__()
        self._nd = nd
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, nd)
        self.stride = _ntuple(stride, nd)
        self.padding = padding
        self.dilation = _ntuple(dilation, nd)
        self.groups = groups
        self.data_format = data_format
        self.output_padding = output_padding
        self._transpose = transpose
        if transpose:
            w_shape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = in_channels // groups * int(np.prod(self.kernel_size))
        std = (2.0 / fan_in) ** 0.5 if fan_in else 1.0
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr, default_initializer=I.Normal(0.0, std))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr, is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr, data_format,
                         transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding,
                                  self.output_padding, self.groups, self.dilation,
                                  output_size, self.data_format)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        return F.max_pool1d(x, *self.args)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool2d(x, *self.args)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode, data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self.args)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        return F.avg_pool1d(x, *self.args)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool2d(x, *self.args)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format, self.output_size = data_format, output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.data_format, self.output_size)

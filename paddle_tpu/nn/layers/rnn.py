"""Recurrent layers: cells + RNN/BiRNN wrappers + multi-layer SimpleRNN/LSTM/GRU.

Reference parity: python/paddle/nn/layer/rnn.py (cells at :380/:480/:600, RNN wrapper
:700+, _RNNBase multi-layer stacks) and the dynamic-rnn runner
python/paddle/fluid/layers/rnn.py:524 (`_maybe_copy` state masking at :517).

TPU-first design: the whole time loop is ONE op — a `jax.lax.scan` kernel dispatched
through `apply`, so XLA sees a single fused scan (no per-step dispatch, no unrolling)
and the backward pass is the scan's vjp. The reference instead emits per-step ops under
a `while_loop` (fluid) or runs cuDNN's fused kernel; lax.scan is the TPU analogue of the
latter. Sequence-length masking matches `_maybe_copy`: states blend by mask, outputs
are emitted raw. Custom (user-defined) cells still work: `RNN` falls back to an eager
per-step loop through the cell's `forward`, exactly like the reference's generic path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...core import dtype as dtypes
from ..layer import Layer
from .. import initializer as I
from ...ops import nn_functional as F_ops
from ...ops import manipulation as P


# ---------------------------------------------------------------- pure steps
def _simple_step(act):
    actfn = jnp.tanh if act == "tanh" else jax.nn.relu

    def step(x, states, params):
        (h,) = states
        w_ih, w_hh = params[0], params[1]
        pre = x @ w_ih.T + h @ w_hh.T
        if len(params) > 2:
            pre = pre + params[2] + params[3]
        return (lambda nh: (nh, (nh,)))(actfn(pre))

    return step


def _lstm_step(x, states, params):
    h, c = states
    w_ih, w_hh = params[0], params[1]
    z = x @ w_ih.T + h @ w_hh.T
    if len(params) > 2:
        z = z + params[2] + params[3]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    nc = f * c + i * jnp.tanh(g)
    nh = o * jnp.tanh(nc)
    return nh, (nh, nc)


def _gru_step(x, states, params):
    (h,) = states
    w_ih, w_hh = params[0], params[1]
    xg = x @ w_ih.T
    hg = h @ w_hh.T
    if len(params) > 2:
        xg = xg + params[2]
        hg = hg + params[3]
    x_r, x_z, x_c = jnp.split(xg, 3, axis=-1)
    h_r, h_z, h_c = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(x_r + h_r)
    z = jax.nn.sigmoid(x_z + h_z)
    c = jnp.tanh(x_c + r * h_c)  # reset gate applied after the matmul
    nh = (h - c) * z + c
    return nh, (nh,)


def _scan_rnn(step, inputs, states, params, sequence_length=None,
              is_reverse=False, time_major=False):
    """One fused scan over time. Returns (outputs, *final_states) Tensors."""
    nst = len(states)
    npar = len(params)

    def kernel(*arrays, nst, npar, rev, tm, has_len):
        x = arrays[0]
        st = tuple(arrays[1:1 + nst])
        par = tuple(arrays[1 + nst:1 + nst + npar])
        seq = arrays[1 + nst + npar] if has_len else None
        xs = x if tm else jnp.swapaxes(x, 0, 1)  # [T, N, I]
        T = xs.shape[0]
        mask = None
        if seq is not None:
            mask = (jnp.arange(T)[:, None] < seq[None, :]).astype(xs.dtype)
            if rev:
                mask = mask[::-1]
        if rev:
            xs = xs[::-1]

        def body(carry, inp):
            if mask is not None:
                x_t, m_t = inp
            else:
                x_t, m_t = inp, None
            out, new = step(x_t, carry, par)
            if m_t is not None:
                m = m_t[:, None]
                new = tuple(m * n + (1 - m) * s for n, s in zip(new, carry))
            return new, out

        xs_in = (xs, mask) if mask is not None else xs
        final, outs = jax.lax.scan(body, st, xs_in)
        if rev:
            outs = outs[::-1]
        outs = outs if tm else jnp.swapaxes(outs, 0, 1)
        return (outs,) + tuple(final)

    tensors = [inputs] + list(states) + list(params)
    has_len = sequence_length is not None
    if has_len:
        tensors.append(sequence_length)
    return apply("rnn_scan", kernel, tensors,
                 {"nst": nst, "npar": npar, "rev": bool(is_reverse),
                  "tm": bool(time_major), "has_len": has_len})


# ---------------------------------------------------------------- cells
class RNNCellBase(Layer):
    """Base for single-step recurrent cells (reference rnn.py:RNNCellBase)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        dtype = dtypes.convert_dtype(dtype or "float32")

        def build(s):
            if isinstance(s, (tuple, list)) and s and isinstance(s[0], (tuple, list)):
                return tuple(build(e) for e in s)
            dims = [batch] + [int(d) for d in (s if isinstance(s, (tuple, list)) else [s])]
            return Tensor(jnp.full(dims, init_value, dtype=dtype))

        s = self.state_shape
        if isinstance(s, tuple) and s and isinstance(s[0], (tuple, list)):
            return tuple(build(e) for e in s)
        return build(s)

    def _make_params(self, gates, input_size, hidden_size, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr):
        std = 1.0 / math.sqrt(hidden_size)
        init = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            (gates * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            (gates * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            (gates * hidden_size,), attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            (gates * hidden_size,), attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def _param_list(self):
        ps = [self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            ps += [self.bias_ih, self.bias_hh]
        return ps

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation for SimpleRNNCell should be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self._make_params(1, input_size, hidden_size, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step_fn(self):
        return _simple_step(self.activation)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out, h = _single_step(self._step_fn(), inputs, (states,), self._param_list())
        return out, h[0]


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(4, input_size, hidden_size, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def _step_fn(self):
        return _lstm_step

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out, st = _single_step(_lstm_step, inputs, tuple(states), self._param_list())
        return out, st


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(3, input_size, hidden_size, weight_ih_attr,
                          weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _step_fn(self):
        return _gru_step

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out, h = _single_step(_gru_step, inputs, (states,), self._param_list())
        return out, h[0]


def _single_step(step, inputs, states, params):
    """Run one cell step as one op (eager cell.forward path)."""
    nst = len(states)

    def kernel(*arrays, nst, npar):
        x = arrays[0]
        st = tuple(arrays[1:1 + nst])
        par = tuple(arrays[1 + nst:1 + nst + npar])
        out, new = step(x, st, par)
        return (out,) + tuple(new)

    outs = apply("rnn_cell_step", kernel, [inputs] + list(states) + list(params),
                 {"nst": nst, "npar": len(params)})
    return outs[0], tuple(outs[1:])


# ---------------------------------------------------------------- wrappers
class RNN(Layer):
    """Run a cell over time (reference rnn.py:RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        if not hasattr(self.cell, "call") and not hasattr(self.cell, "forward"):
            raise ValueError("RNN needs a cell with a forward method")
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        if initial_states is None:
            batch_idx = 1 if self.time_major else 0
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)

        if isinstance(self.cell, (SimpleRNNCell, LSTMCell, GRUCell)) and not kwargs:
            states = (tuple(initial_states) if isinstance(initial_states, (tuple, list))
                      else (initial_states,))
            outs = _scan_rnn(self.cell._step_fn(), inputs, states,
                             self.cell._param_list(), sequence_length,
                             self.is_reverse, self.time_major)
            outputs, final = outs[0], outs[1:]
            if isinstance(self.cell, LSTMCell):
                return outputs, tuple(final)
            return outputs, final[0]
        return self._eager_loop(inputs, initial_states, sequence_length, **kwargs)

    def _eager_loop(self, inputs, states, sequence_length=None, **kwargs):
        """Generic path for user-defined cells: per-step cell.forward calls."""
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        mask = None
        if sequence_length is not None:
            mask = F_ops.sequence_mask(sequence_length, maxlen=T, dtype="float32")
        outs = [None] * T
        for t in steps:
            x_t = inputs[:, t] if time_axis == 1 else inputs[t]
            out, new_states = self.cell(x_t, states, **kwargs)
            if mask is not None:
                m = mask[:, t].unsqueeze(-1)
                flat_new = new_states if isinstance(new_states, (tuple, list)) else [new_states]
                flat_old = states if isinstance(states, (tuple, list)) else [states]
                blended = [m * n + (1.0 - m) * o for n, o in zip(flat_new, flat_old)]
                new_states = (type(new_states)(blended)
                              if isinstance(new_states, (tuple, list)) else blended[0])
            outs[t] = out
            states = new_states
        outputs = P.stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    """Forward + backward cells over the same input (reference rnn.py:BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length, **kwargs)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length, **kwargs)
        outputs = P.concat([out_fw, out_bw], axis=-1)
        return outputs, (st_fw, st_bw)


# ---------------------------------------------------------------- stacks
class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        if direction in ("bidirectional", "bidirect"):
            self.num_directions = 2
        elif direction == "forward":
            self.num_directions = 1
        else:
            raise ValueError(f"direction should be forward or bidirect(ional), got {direction}")
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.state_components = 2 if mode == "LSTM" else 1

        kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                  bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)

        def make_cell(in_size):
            if mode == "LSTM":
                return LSTMCell(in_size, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(in_size, hidden_size, **kw)
            return SimpleRNNCell(in_size, hidden_size, activation, **kw)

        from .container import LayerList

        self._all_layers = LayerList()
        for layer in range(num_layers):
            in_size = input_size if layer == 0 else hidden_size * self.num_directions
            if self.num_directions == 2:
                self._all_layers.append(BiRNN(make_cell(in_size), make_cell(in_size),
                                              time_major))
            else:
                self._all_layers.append(RNN(make_cell(in_size), False, time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        D, L, C = self.num_directions, self.num_layers, self.state_components
        batch_idx = 1 if self.time_major else 0
        batch = inputs.shape[batch_idx]

        if initial_states is None:
            zeros = lambda: Tensor(jnp.zeros((L * D, batch, self.hidden_size),
                                             dtypes.convert_dtype("float32")))
            initial_states = (zeros(), zeros()) if C == 2 else zeros()

        comp = list(initial_states) if C == 2 else [initial_states]
        # [L*D, N, H] -> per (layer, direction) slices
        per_layer = []
        for layer in range(L):
            if D == 2:
                fw = tuple(c[2 * layer] for c in comp)
                bw = tuple(c[2 * layer + 1] for c in comp)
                per_layer.append((fw if C == 2 else fw[0],
                                  bw if C == 2 else bw[0]))
            else:
                st = tuple(c[layer] for c in comp)
                per_layer.append(st if C == 2 else st[0])

        x = inputs
        finals = []
        for layer in range(L):
            x, st = self._all_layers[layer](x, per_layer[layer], sequence_length)
            finals.append(st)
            if self.dropout > 0.0 and layer < L - 1:
                x = F_ops.dropout(x, p=self.dropout, training=self.training)

        # restack final states into [L*D, N, H] (x C components for LSTM)
        comps_out = [[] for _ in range(C)]
        for st in finals:
            dirs = st if D == 2 else (st,)
            for d_st in dirs:
                parts = d_st if C == 2 else (d_st,)
                for i, p in enumerate(parts):
                    comps_out[i].append(p)
        stacked = [P.stack(c, axis=0) for c in comps_out]
        final_states = tuple(stacked) if C == 2 else stacked[0]
        return x, final_states


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("RNN_TANH" if activation == "tanh" else "RNN_RELU",
                         input_size, hidden_size, num_layers, direction, time_major,
                         dropout, activation, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, "tanh", weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)

"""Weight initializers. Reference: python/paddle/fluid/initializer.py + python/paddle/nn/initializer/."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtypes
from ..core import random as random_mod


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels, paddle layout [out_c, in_c, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0, "conv3d": 1.0,
        "tanh": 5.0 / 3, "relu": math.sqrt(2.0), "selu": 3.0 / 4,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtypes.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = random_mod.named_generator("init").next_key()
        return jax.random.normal(key, shape, dtypes.convert_dtype(dtype)) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = random_mod.named_generator("init").next_key()
        return (jax.random.truncated_normal(key, -2.0, 2.0, shape,
                                            dtypes.convert_dtype(dtype)) * self.std + self.mean)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = random_mod.named_generator("init").next_key()
        return jax.random.uniform(key, shape, dtypes.convert_dtype(dtype), self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = random_mod.named_generator("init").next_key()
        return jax.random.normal(key, shape, dtypes.convert_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = random_mod.named_generator("init").next_key()
        return jax.random.uniform(key, shape, dtypes.convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        key = random_mod.named_generator("init").next_key()
        return jax.random.normal(key, shape, dtypes.convert_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        key = random_mod.named_generator("init").next_key()
        return jax.random.uniform(key, shape, dtypes.convert_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        from ..core.tensor import Tensor

        if isinstance(value, Tensor):
            value = np.asarray(value._data)
        self.value = np.asarray(value)

    def __call__(self, shape, dtype):
        v = self.value.reshape(shape).astype(dtypes.convert_dtype(dtype))
        return jnp.asarray(v)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        key = random_mod.named_generator("init").next_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (rows, cols) if rows >= cols else (cols, rows)
        a = jax.random.normal(key, flat, jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q.reshape(shape)).astype(dtypes.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(oc // self.groups, ic)):
                out[(g * (oc // self.groups) + i, i) + mid] = 1.0
        return jnp.asarray(out.astype(dtypes.convert_dtype(dtype)))


# lowercase aliases (paddle.nn.initializer exports both in places)
constant = Constant
normal = Normal
uniform = Uniform


class Bilinear(Initializer):
    """Bilinear upsampling kernel init for transposed convs (reference
    nn/initializer/Bilinear): weight shape [C_out, C_in, k, k]."""

    def __call__(self, shape, dtype):
        import numpy as _np

        w = _np.zeros(shape, dtype=_np.float32)
        k = shape[-1]
        f = int(_np.ceil(k / 2.0))
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(_np.prod(shape)):
            x = i % k
            y = (i // k) % k
            w.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        import jax.numpy as _jnp

        return _jnp.asarray(w.astype(_np.dtype(dtype)))


_global_initializer = {}


def set_global_initializer(weight_init, bias_init=None):
    """Override default initializers for subsequently created parameters
    (reference nn/initializer/set_global_initializer). Pass None to reset."""
    _global_initializer["weight"] = weight_init
    _global_initializer["bias"] = bias_init


def _global_default(is_bias):
    return _global_initializer.get("bias" if is_bias else "weight")

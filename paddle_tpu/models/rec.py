"""Recommendation models for the parameter-server benchmark (BASELINE config 5:
Wide&Deep / DeepFM, examples/sec on TPU workers + CPU PS).

Reference analogue: the PS tests' CTR models (python/paddle/fluid/tests/unittests/
ps/ wide&deep-style dist models built on sparse_embedding +
distributed_lookup_table, operators/pscore/distributed_lookup_table_op.cc).
Sparse embedding tables can live on the parameter server (DistributedEmbedding —
trainer holds no rows, pulls on forward / pushes grads on backward) or fall back
to a dense trainer-side nn.Embedding for single-process runs; the dense tower
runs on the TPU either way.
"""
from __future__ import annotations

import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..distributed.ps.layers import DistributedEmbedding
from ..nn import functional as F
from ..ops import manipulation as P
from ..ops import math as M
from ..ops import reduction as R


class _SparseFeatures(nn.Layer):
    """Embeds `num_fields` categorical id fields into [b, fields, dim]."""

    def __init__(self, sparse_feature_dim, embedding_dim, num_fields,
                 use_ps=False, table_id=0, client=None):
        super().__init__()
        self.use_ps = use_ps
        self.num_fields = num_fields
        self.embedding_dim = embedding_dim
        if use_ps:
            self.emb = DistributedEmbedding(table_id, embedding_dim, client)
        else:
            self.emb = nn.Embedding(sparse_feature_dim, embedding_dim,
                                    sparse=True)

    def forward(self, sparse_ids):  # [b, fields]
        return self.emb(sparse_ids)  # [b, fields, dim]


class WideDeep(nn.Layer):
    """Wide (linear over sparse) + Deep (MLP over embeddings + dense feats).

    forward(sparse_ids [b, F] int64, dense [b, D] f32) -> logits [b, 1]
    """

    def __init__(self, sparse_feature_dim=100000, embedding_dim=8, num_fields=26,
                 dense_dim=13, hidden_sizes=(128, 64, 32), use_ps=False,
                 wide_table_id=0, deep_table_id=1, client=None):
        super().__init__()
        self.num_fields = num_fields
        # wide part: per-id scalar weight == embedding with dim 1
        if use_ps:
            self.wide_emb = DistributedEmbedding(wide_table_id, 1, client)
        else:
            self.wide_emb = nn.Embedding(sparse_feature_dim, 1, sparse=True)
        self.deep_emb = _SparseFeatures(sparse_feature_dim, embedding_dim,
                                        num_fields, use_ps, deep_table_id, client)
        sizes = [num_fields * embedding_dim + dense_dim] + list(hidden_sizes)
        self.mlp = nn.LayerList([nn.Linear(sizes[i], sizes[i + 1])
                                 for i in range(len(sizes) - 1)])
        self.out = nn.Linear(hidden_sizes[-1], 1)

    def forward(self, sparse_ids, dense):
        wide = R.sum(self.wide_emb(sparse_ids), axis=1)         # [b, 1]
        deep = self.deep_emb(sparse_ids)                        # [b, F, dim]
        x = P.concat([P.reshape(deep, (deep.shape[0], -1)), dense], axis=1)
        for fc in self.mlp:
            x = F.relu(fc(x))
        return self.out(x) + wide


class DeepFM(nn.Layer):
    """Factorization machine (1st + 2nd order over field embeddings) + deep MLP.

    forward(sparse_ids [b, F] int64, dense [b, D] f32) -> logits [b, 1]
    """

    def __init__(self, sparse_feature_dim=100000, embedding_dim=8, num_fields=26,
                 dense_dim=13, hidden_sizes=(128, 64), use_ps=False,
                 first_table_id=0, second_table_id=1, client=None):
        super().__init__()
        if use_ps:
            self.first_emb = DistributedEmbedding(first_table_id, 1, client)
        else:
            self.first_emb = nn.Embedding(sparse_feature_dim, 1, sparse=True)
        self.second_emb = _SparseFeatures(sparse_feature_dim, embedding_dim,
                                          num_fields, use_ps, second_table_id,
                                          client)
        sizes = [num_fields * embedding_dim + dense_dim] + list(hidden_sizes)
        self.mlp = nn.LayerList([nn.Linear(sizes[i], sizes[i + 1])
                                 for i in range(len(sizes) - 1)])
        self.out = nn.Linear(hidden_sizes[-1], 1)

    def forward(self, sparse_ids, dense):
        first = R.sum(self.first_emb(sparse_ids), axis=1)       # [b, 1]
        emb = self.second_emb(sparse_ids)                       # [b, F, d]
        # FM 2nd order: 0.5 * ((sum v)^2 - sum v^2), summed over dim
        sum_sq = M.pow(R.sum(emb, axis=1), 2)
        sq_sum = R.sum(M.pow(emb, 2), axis=1)
        fm2 = 0.5 * R.sum(sum_sq - sq_sum, axis=1, keepdim=True)  # [b, 1]
        x = P.concat([P.reshape(emb, (emb.shape[0], -1)), dense], axis=1)
        for fc in self.mlp:
            x = F.relu(fc(x))
        return self.out(x) + first + fm2


def ctr_loss(logits, label):
    """BCE-with-logits click loss used by both CTR models."""
    return F.binary_cross_entropy_with_logits(logits, label.astype("float32"))

from .gpt import (  # noqa: F401
    GPTConfig, GPTForPretraining, GPTForPretrainingPipe, GPTModel, gpt_tiny,
    gpt_1p3b, gpt_345m,
)

from .gpt import (  # noqa: F401
    GPTConfig, GPTForPretraining, GPTForPretrainingPipe, GPTModel, gpt_tiny,
    gpt_1p3b, gpt_345m,
)
from .ernie import (  # noqa: F401
    BertConfig, BertForPretraining, BertModel, ErnieConfig, ErnieForPretraining,
    ErnieModel, bert_base, bert_large, ernie_base, ernie_large, ernie_tiny,
)
from .rec import DeepFM, WideDeep, ctr_loss  # noqa: F401

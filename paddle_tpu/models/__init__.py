from .gpt import GPTConfig, GPTForPretraining, GPTModel, gpt_tiny, gpt_1p3b, gpt_345m  # noqa: F401

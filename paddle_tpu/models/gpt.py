"""GPT family — the flagship model for the distributed benchmarks.

Reference analogue: the ERNIE/GPT models fleet's hybrid-parallel examples train
(hybrid_parallel_mp_layers.py / GPT-3 config in BASELINE.json). Built from the
meta_parallel TP layers so every parameter carries its PartitionSpec dist_attr —
under the TrainStepEngine pjit step this yields Megatron-style tensor parallelism
(column→row pairs, vocab-parallel embedding + loss) with GSPMD inserting the
collectives; dp/sharding/sp come from batch & optimizer-state shardings.

bf16-first: matmul inputs autocast under amp; layernorm/softmax/loss stay f32.
"""
from __future__ import annotations

import math

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.utils import recompute
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from ..ops import creation as C
from ..ops import manipulation as P
from ..nn import functional as F


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
                 ffn_hidden_size=None, max_seq_len=1024, dropout=0.0,
                 attention_dropout=0.0, use_recompute=False, dtype="float32",
                 tie_word_embeddings=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.use_recompute = use_recompute
        self.dtype = dtype
        self.tie_word_embeddings = tie_word_embeddings


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
                     max_seq_len=128, **kw)


def gpt_345m(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
                     max_seq_len=1024, **kw)


def gpt_1p3b(**kw):
    """GPT-3 1.3B (BASELINE config 4)."""
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(config.hidden_size, 3 * config.hidden_size,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(config.hidden_size, config.hidden_size,
                                          input_is_parallel=True)
        self.attn_dropout = config.attention_dropout

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h] (h sharded over mp)
        qkv = P.reshape(qkv, (b, s, 3, self.num_heads, self.head_dim))
        q, k, v = P.unbind(qkv, axis=2)  # heads dim sharded over mp under pjit
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_dropout, training=self.training)
        out = P.reshape(out, (b, s, self.hidden_size))
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc1 = ColumnParallelLinear(config.hidden_size, config.ffn_hidden_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.ffn_hidden_size, config.hidden_size,
                                     input_is_parallel=True)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.mlp = GPTMLP(config)
        self.dropout = config.dropout
        self.use_recompute = config.use_recompute

    def _forward(self, x):
        h = x + F.dropout(self.attn(self.ln1(x)), self.dropout, training=self.training)
        return h + F.dropout(self.mlp(self.ln2(h)), self.dropout, training=self.training)

    def forward(self, x):
        if self.use_recompute and self.training:
            return recompute(self._forward, x)
        return self._forward(x)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = C.arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)


class GPTForPretraining(nn.Layer):
    """forward(input_ids, labels) -> scalar LM loss (the engine's expected signature)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config
        if config.tie_word_embeddings:
            self.lm_head = None  # reuse wte.weight (vocab-parallel)
        else:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=False)
        self.loss_fn = ParallelCrossEntropy()

    def logits(self, input_ids):
        h = self.gpt(input_ids)
        if self.lm_head is None:
            from ..ops import linalg as L

            return L.matmul(h, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(h)

    def forward(self, input_ids, labels=None):
        logits = self.logits(input_ids)
        if labels is None:
            return logits
        loss = self.loss_fn(logits, labels)
        from ..ops import reduction as R

        return R.mean(loss)

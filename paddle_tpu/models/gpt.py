"""GPT family — the flagship model for the distributed benchmarks.

Reference analogue: the ERNIE/GPT models fleet's hybrid-parallel examples train
(hybrid_parallel_mp_layers.py / GPT-3 config in BASELINE.json). Built from the
meta_parallel TP layers so every parameter carries its PartitionSpec dist_attr —
under the TrainStepEngine pjit step this yields Megatron-style tensor parallelism
(column→row pairs, vocab-parallel embedding + loss) with GSPMD inserting the
collectives; dp/sharding/sp come from batch & optimizer-state shardings.

bf16-first: matmul inputs autocast under amp; layernorm/softmax/loss stay f32.
"""
from __future__ import annotations

import math

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.utils import recompute
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from ..ops import creation as C
from ..ops import manipulation as P
from ..nn import functional as F


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
                 ffn_hidden_size=None, max_seq_len=1024, dropout=0.0,
                 attention_dropout=0.0, use_recompute=False,
                 recompute_granularity="full", dtype="float32",
                 tie_word_embeddings=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.use_recompute = use_recompute
        # "full" | "selective" (reference recompute_configs granularity):
        # selective saves matmul outputs and recomputes only elementwise ops
        self.recompute_granularity = recompute_granularity
        self.dtype = dtype
        self.tie_word_embeddings = tie_word_embeddings


def gpt_tiny(**kw):
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
                     max_seq_len=128, **kw)


def gpt_345m(**kw):
    return GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24, num_heads=16,
                     max_seq_len=1024, **kw)


def gpt_1p3b(**kw):
    """GPT-3 1.3B (BASELINE config 4)."""
    return GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16,
                     max_seq_len=2048, **kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(config.hidden_size, 3 * config.hidden_size,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(config.hidden_size, config.hidden_size,
                                          input_is_parallel=True)
        self.attn_dropout = config.attention_dropout

    def forward(self, x, cache=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [b, s, 3h] (h sharded over mp)
        qkv = P.reshape(qkv, (b, s, 3, self.num_heads, self.head_dim))
        q, k, v = P.unbind(qkv, axis=2)  # heads dim sharded over mp under pjit
        if cache is None:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.attn_dropout,
                training=self.training)
            out = P.reshape(out, (b, s, self.hidden_size))
            return self.out_proj(out)

        # KV-cache decode (TPU-native: fixed [b, T, nh, hd] buffers updated
        # with dynamic_update_slice, so the whole decode loop is one static-
        # shape scan). cache = (k_cache, v_cache, offset): offset is the count
        # of already-cached positions; the new chunk writes [offset, offset+s).
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        if hasattr(cache, "page_table"):
            # paged serving cache (serving/kv_pages.py): scatter this
            # chunk's K/V through the slot page table, gather the logical
            # cache back (dequantizing int8 pages), and mask exactly like
            # the per-row dense path — unallocated table entries alias the
            # zero page, so the gathered values match a zero-initialized
            # contiguous cache bit for bit.
            from ..serving import kv_pages as _kvp

            kc, vc, new_cache = _kvp.update_and_read(cache, k._data, v._data)
            total = kc.shape[1]
            off = cache.offset
            qpos = off[:, None] + jnp.arange(s)[None, :]      # [b, s]
            mask = (jnp.arange(total)[None, None, :]
                    <= qpos[:, :, None])[:, None]             # [b, 1, s, T]
            out = F.scaled_dot_product_attention(
                q, Tensor(kc), Tensor(vc), attn_mask=Tensor(mask),
                dropout_p=0.0, training=False)
            out = P.reshape(out, (b, s, self.hidden_size))
            return self.out_proj(out), new_cache

        k_cache, v_cache, offset = cache
        kc, vc = k_cache._data, v_cache._data
        off = offset._data if isinstance(offset, Tensor) else offset
        off = off.astype(jnp.int32)
        total = kc.shape[1]
        if getattr(off, "ndim", 0) == 1:
            # per-row offsets (serving slot cache): each row writes its new
            # chunk at its own position. Rows past a row's offset are never
            # attended (mask below), so retired/short slots stay inert and
            # one batched step can serve slots at arbitrary depths.
            rows = jnp.arange(b)[:, None]                     # [b, 1]
            pos = jnp.clip(off[:, None] + jnp.arange(s)[None, :], 0, total - 1)
            kc = kc.at[rows, pos].set(k._data.astype(kc.dtype))
            vc = vc.at[rows, pos].set(v._data.astype(vc.dtype))
            qpos = off[:, None] + jnp.arange(s)[None, :]      # [b, s]
            mask = (jnp.arange(total)[None, None, :]
                    <= qpos[:, :, None])[:, None]             # [b, 1, s, T]
        else:
            zero = jnp.int32(0)
            kc = jax.lax.dynamic_update_slice(
                kc, k._data.astype(kc.dtype), (zero, off, zero, zero))
            vc = jax.lax.dynamic_update_slice(
                vc, v._data.astype(vc.dtype), (zero, off, zero, zero))
            qpos = off + jnp.arange(s)                       # [s]
            mask = jnp.arange(total)[None, :] <= qpos[:, None]  # [s, T]
        out = F.scaled_dot_product_attention(
            q, Tensor(kc), Tensor(vc), attn_mask=Tensor(mask),
            dropout_p=0.0, training=False)
        out = P.reshape(out, (b, s, self.hidden_size))
        return self.out_proj(out), (Tensor(kc), Tensor(vc),
                                    Tensor(off + jnp.int32(s)))


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc1 = ColumnParallelLinear(config.hidden_size, config.ffn_hidden_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.ffn_hidden_size, config.hidden_size,
                                     input_is_parallel=True)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x), approximate=True))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.attn = GPTAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.mlp = GPTMLP(config)
        self.dropout = config.dropout
        self.use_recompute = config.use_recompute
        self.recompute_granularity = getattr(config, "recompute_granularity",
                                             "full")

    def _forward(self, x):
        h = x + F.dropout(self.attn(self.ln1(x)), self.dropout, training=self.training)
        return h + F.dropout(self.mlp(self.ln2(h)), self.dropout, training=self.training)

    def forward(self, x, cache=None):
        if cache is not None:
            a, new_cache = self.attn(self.ln1(x), cache=cache)
            h = x + a
            return h + self.mlp(self.ln2(h)), new_cache
        if self.use_recompute and self.training:
            return recompute(self._forward, x,
                             policy=self.recompute_granularity)
        return self._forward(x)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([GPTBlock(config) for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids, caches=None):
        s = input_ids.shape[1]
        if caches is not None:
            from ..core.tensor import Tensor

            off = caches[0][2]
            off_arr = off._data if isinstance(off, Tensor) else off
            import jax.numpy as jnp

            if getattr(off_arr, "ndim", 0) == 1:  # per-row offsets -> [b, s]
                pos = Tensor(off_arr[:, None].astype(jnp.int64)
                             + jnp.arange(s, dtype=jnp.int64)[None, :])
            else:
                pos = Tensor(off_arr + jnp.arange(s, dtype=jnp.int64))
        else:
            pos = C.arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        x = self.drop(x)
        if caches is not None:
            new_caches = []
            for blk, cache in zip(self.blocks, caches):
                x, c = blk(x, cache=cache)
                new_caches.append(c)
            return self.ln_f(x), new_caches
        for blk in self.blocks:
            x = blk(x)
        return self.ln_f(x)

    @staticmethod
    def fsdp_layer_key(name: str) -> str:
        """FSDP bucket granularity: one bucket per transformer block (the
        unit whose all-gather should hide under the previous block's
        matmuls), the token/position embeddings together, and everything
        else (final norm) in one tail bucket. Name-prefix based so it works
        for both GPTModel params and GPTForPretraining's 'gpt.'-qualified
        view of them."""
        import re

        m = re.match(r"(.*\bblocks\.\d+)\.", name)
        if m:
            return m.group(1)
        if ".wte." in name or ".wpe." in name or \
                name.startswith(("wte.", "wpe.")):
            return "embeddings"
        return "final"


class GPTForPretrainingPipe(nn.Layer):
    """Pipeline-parallel GPT (the reference's GPTForPretrainingPipe/PipelineLayer
    analogue, fleet/meta_parallel/pp_layers.py:159 + pipeline_parallel.py:31).

    The transformer body is stored as stacked per-stage parameters with leading dims
    [S, L/S, ...] where S = pp degree: the 'pp' mesh axis shards the stage dim, 'mp'
    shards the Megatron dims, and the body executes as an SPMD scan+ppermute pipeline
    (distributed/pipeline_schedule.py). Embedding / final-LN / loss are replicated over
    pp (computed identically on every pp rank — they are outside the bubble), matching
    the reference's shared-embedding stages without the p2p tie-grad allreduce.

    forward(input_ids, labels) -> scalar LM loss, same engine signature as
    GPTForPretraining; with pp degree 1 it degrades to a plain scan over all layers.
    """

    def __init__(self, config: GPTConfig, num_stages=None, num_microbatches=None,
                 num_virtual_stages=1):
        super().__init__()
        from jax.sharding import PartitionSpec as PS

        from ..distributed.mesh import get_hybrid_communicate_group
        from ..nn import initializer as I

        hcg = get_hybrid_communicate_group()
        self.config = config
        if config.dropout or config.attention_dropout:
            raise ValueError(
                "GPTForPretrainingPipe does not support dropout yet (needs per-stage "
                "RNG plumbing through the SPMD schedule); set dropout=0")
        self.num_stages = int(num_stages or (hcg.degrees["pp"] if hcg else 1))
        # interleaved (virtual-stage) 1F1B: each pp rank holds V chunks of
        # layers (logical stage v*P + r), cutting the pipeline bubble ~V-fold
        # (reference SectionWorker interleaving, device_worker.h:615)
        self.num_virtual_stages = int(num_virtual_stages)
        total_stages = self.num_stages * self.num_virtual_stages
        if config.num_layers % total_stages != 0:
            raise ValueError(
                f"num_layers {config.num_layers} not divisible by pp x virtual "
                f"= {self.num_stages} x {self.num_virtual_stages}")
        self.layers_per_stage = config.num_layers // total_stages
        self.num_microbatches = int(num_microbatches or max(1, self.num_stages))

        H, FF = config.hidden_size, config.ffn_hidden_size
        S, Lp, V = self.num_stages, self.layers_per_stage, self.num_virtual_stages
        self.wte = VocabParallelEmbedding(config.vocab_size, H)
        self.wpe = nn.Embedding(config.max_seq_len, H)
        self.ln_f = nn.LayerNorm(H)
        self.loss_fn = ParallelCrossEntropy()

        def mk(name, shape, spec, init):
            if V > 1 and len(spec) > 0 and spec[0] == "pp":
                # stage-stacked params only: leading dims [V, S], leaf
                # [v, r] = logical stage v*S + r, so P(None, "pp") places
                # each rank's V chunks where the interleaved schedule
                # executes them. Non-stage params (lm_head_w) keep their
                # shape.
                shape = (V,) + shape
                spec = PS(None, *spec)
            p = self.create_parameter(shape, default_initializer=init)
            p.dist_attr = spec
            self.add_parameter(name, p)

        w = I.Normal(std=0.02)
        zeros, ones = I.Constant(0.0), I.Constant(1.0)
        mk("qkv_w", (S, Lp, H, 3 * H), PS("pp", None, None, "mp"), w)
        mk("qkv_b", (S, Lp, 3 * H), PS("pp", None, "mp"), zeros)
        mk("proj_w", (S, Lp, H, H), PS("pp", None, "mp", None), w)
        mk("proj_b", (S, Lp, H), PS("pp"), zeros)
        mk("ln1_s", (S, Lp, H), PS("pp"), ones)
        mk("ln1_b", (S, Lp, H), PS("pp"), zeros)
        mk("ln2_s", (S, Lp, H), PS("pp"), ones)
        mk("ln2_b", (S, Lp, H), PS("pp"), zeros)
        mk("fc1_w", (S, Lp, H, FF), PS("pp", None, None, "mp"), w)
        mk("fc1_b", (S, Lp, FF), PS("pp", None, "mp"), zeros)
        mk("fc2_w", (S, Lp, FF, H), PS("pp", None, "mp", None), w)
        mk("fc2_b", (S, Lp, H), PS("pp"), zeros)
        if not config.tie_word_embeddings:
            mk("lm_head_w", (H, config.vocab_size), PS(None, "mp"), w)

    _STACKED = ("qkv_w", "qkv_b", "proj_w", "proj_b", "ln1_s", "ln1_b",
                "ln2_s", "ln2_b", "fc1_w", "fc1_b", "fc2_w", "fc2_b")
    _pipeline_stacked = True  # fleet.distributed_model pp-mode marker

    def forward(self, input_ids, labels=None):
        import jax
        import jax.numpy as jnp

        from ..core.dispatch import apply
        from ..distributed.mesh import get_hybrid_communicate_group
        from ..distributed.pipeline_schedule import (
            microbatch_merge, microbatch_split, spmd_pipeline)
        from ..jit import in_jit_trace

        cfg = self.config
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        s = input_ids.shape[1]
        pos = C.arange(0, s, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)

        hcg = get_hybrid_communicate_group()
        use_spmd = (in_jit_trace() and hcg is not None
                    and hcg.degrees["pp"] == self.num_stages)
        mesh = hcg.mesh if use_spmd else None
        n_micro = self.num_microbatches

        use_recompute = cfg.use_recompute
        if use_recompute:
            from ..distributed.fleet.utils import _resolve_policy

            remat_policy = _resolve_policy(
                getattr(cfg, "recompute_granularity", "full"))

        V = self.num_virtual_stages

        def kernel(xa, *flat):
            params = dict(zip(self._STACKED, flat))
            def body(lp, h):
                def one(h, layer):
                    return _pipe_block_fwd(h, layer, nh, hd), None
                if use_recompute:  # recompute_interval analogue: checkpoint each block
                    one = jax.checkpoint(one, policy=remat_policy)
                h, _ = jax.lax.scan(one, h, lp)
                return h
            if mesh is not None:
                from ..distributed.pipeline_schedule import \
                    spmd_pipeline_interleaved

                mb = microbatch_split(xa, n_micro)
                if V > 1:
                    return microbatch_merge(spmd_pipeline_interleaved(
                        body, params, mb, mesh, "pp", num_chunks=V))
                return microbatch_merge(spmd_pipeline(body, params, mb, mesh, "pp"))
            # single-program fallback: same math, all stages scanned in
            # sequence (leading [V, S] or [S] dims flatten in logical-stage
            # order either way — chunk-major matches execution order)
            n_lead = 3 if V > 1 else 2
            merged = jax.tree.map(
                lambda l: l.reshape((math.prod(l.shape[:n_lead]),)
                                    + l.shape[n_lead:]), params)
            return body(merged, xa)

        h = apply("gpt_pipe_body", kernel, [x] + [getattr(self, n) for n in self._STACKED])
        h = self.ln_f(h)
        from ..ops import linalg as L
        from ..ops import reduction as R

        mp_deg = hcg.degrees["mp"] if hcg is not None else 1
        if labels is not None and cfg.tie_word_embeddings and mp_deg <= 1:
            # chunked fused LM loss (ops/fused.py), as in GPTForPretraining
            from ..ops.fused import fused_linear_cross_entropy

            loss = fused_linear_cross_entropy(h, self.wte.weight, labels,
                                              transpose_y=True,
                                              ignore_index=self.loss_fn.ignore_index)
            return R.mean(loss)
        if cfg.tie_word_embeddings:
            logits = L.matmul(h, self.wte.weight, transpose_y=True)
        else:
            logits = L.matmul(h, self.lm_head_w)
        if labels is None:
            return logits
        return R.mean(self.loss_fn(logits, labels))


def _pipe_block_fwd(x, p, nh, hd):
    """One transformer block in plain jnp (runs inside shard_map/scan).

    LayerNorm/softmax in f32, matmuls in the input dtype (bf16 under amp) — the same
    numerics as GPTBlock's ops-path forward.
    """
    import jax
    import jax.numpy as jnp

    def ln(h, scale, bias):
        hf = h.astype(jnp.float32)
        mu = jnp.mean(hf, -1, keepdims=True)
        var = jnp.mean(jnp.square(hf - mu), -1, keepdims=True)
        return ((hf - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias).astype(h.dtype)

    b, s, H = x.shape
    h = ln(x, p["ln1_s"], p["ln1_b"])
    qkv = h @ p["qkv_w"] + p["qkv_b"]
    qkv = qkv.reshape(b, s, 3, nh, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / math.sqrt(hd)
    mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
    scores = jnp.where(mask[None, None], scores, -1e30)
    attn = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    o = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(b, s, nh * hd)
    x = x + o @ p["proj_w"] + p["proj_b"]
    h2 = ln(x, p["ln2_s"], p["ln2_b"])
    m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"], approximate=True)
    return x + m @ p["fc2_w"] + p["fc2_b"]


def _decode_exec_registry(model):
    """Per-model decode ExecutableRegistry (generate/generate_beam).

    One registry instance per model, keyed by the full sampling/shape tuple
    and bounded live by FLAGS_decode_jit_cache_size, so traffic cycling
    through sampling configs cannot grow the per-model store without bound.
    Legacy core.monitor counters ride as registry aliases:
    decode.jit_compiles (new executables), decode.cache_evictions (LRU
    drops)."""
    from ..core import flags as _flags
    from ..core.exec_registry import ExecutableRegistry

    reg = model.__dict__.get("_decode_exec_registry")
    if not isinstance(reg, ExecutableRegistry):
        reg = model.__dict__["_decode_exec_registry"] = ExecutableRegistry(
            name="gpt.decode",
            capacity=lambda: int(_flags.flag("decode_jit_cache_size")),
            miss_counter="decode.jit_compiles",
            eviction_counter="decode.cache_evictions")
    return reg


def _decode_jit_get(model, key, build):
    """Decode-executable lookup through the model's ExecutableRegistry; the
    label (key[0]) distinguishes greedy/sampled generate from beam search in
    registry telemetry."""
    reg = _decode_exec_registry(model)
    return reg.get_or_build(key, build, label=key[0]).fn


class GPTForPretraining(nn.Layer):
    """forward(input_ids, labels) -> scalar LM loss (the engine's expected signature)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config
        if config.tie_word_embeddings:
            self.lm_head = None  # reuse wte.weight (vocab-parallel)
        else:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=False)
        self.loss_fn = ParallelCrossEntropy()

    def logits(self, input_ids):
        h = self.gpt(input_ids)
        if self.lm_head is None:
            from ..ops import linalg as L

            return L.matmul(h, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(h)

    def forward(self, input_ids, labels=None):
        from ..ops import reduction as R

        if labels is not None and self._can_fuse_loss():
            # chunked LM-head+CE (ops/fused.py): skips the [b, s, vocab] f32
            # logits materialization — the dominant activation of the step
            from ..ops.fused import fused_linear_cross_entropy

            h = self.gpt(input_ids)
            loss = fused_linear_cross_entropy(h, self.gpt.wte.weight, labels,
                                              transpose_y=True,
                                              ignore_index=self.loss_fn.ignore_index)
            return R.mean(loss)
        logits = self.logits(input_ids)
        if labels is None:
            return logits
        loss = self.loss_fn(logits, labels)
        return R.mean(loss)

    # param names here are 'gpt.blocks.N.*' / 'gpt.wte.*' / 'lm_head.*';
    # the prefix-insensitive key delegates cleanly
    fsdp_layer_key = staticmethod(GPTModel.fsdp_layer_key)

    def _can_fuse_loss(self):
        if self.lm_head is not None:
            return False
        from ..distributed.mesh import get_hybrid_communicate_group

        hcg = get_hybrid_communicate_group()
        # vocab-sharded weight (mp > 1) keeps the vocab-parallel psum loss path
        return hcg is None or hcg.degrees["mp"] <= 1

    def _head_logits(self, h):
        """Hidden states -> vocab logits (shared by forward and decode)."""
        if self.lm_head is None:
            from ..ops import linalg as L

            return L.matmul(h, self.gpt.wte.weight, transpose_y=True)
        return self.lm_head(h)

    def decode_exec_registry(self):
        """This model's decode ExecutableRegistry (generate/generate_beam
        executables, LRU-bounded by FLAGS_decode_jit_cache_size). Public so
        benches/tests can inspect or clear the decode executable set."""
        return _decode_exec_registry(self)

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None, seed=0,
                 decode_strategy=None, num_beams=1, length_penalty=1.0,
                 prompt_bucket=None):
        """Autoregressive decode with KV cache — ONE jitted program: prefill
        fills fixed [b, total, nh, hd] cache buffers, then a lax.scan emits a
        token per step (static shapes end to end, the TPU-native decode loop).
        Greedy when temperature == 0; top-k/top-p nucleus sampling otherwise.
        After eos_token_id every subsequent position repeats eos.

        decode_strategy follows the reference generate() API: None picks
        greedy/sampling from temperature; "beam_search" (or num_beams > 1)
        routes to generate_beam.

        prompt_bucket (opt-in): an int target length or a ladder of rungs
        (e.g. serving.DEFAULT_LADDER) — the prompt is right-padded to the
        smallest rung >= its length and the executable is keyed on the RUNG,
        so every prompt length in a bucket shares one compiled program.
        Causal attention makes the pad harmless: logits are read at the last
        real position and decode resumes at offset=prompt_len, overwriting
        one pad cache row per generated token before it is ever attended —
        tokens are identical to the unpadded run.

        Single-replica inference path (mp decode would shard the head and
        psum logits; see PARITY row 49). Returns [b, prompt + max_new_tokens].
        """
        if decode_strategy not in (None, "greedy_search", "sampling",
                                   "beam_search"):
            raise ValueError(
                f"decode_strategy must be 'greedy_search', 'sampling' or "
                f"'beam_search', got {decode_strategy!r}")
        if decode_strategy == "beam_search" or (decode_strategy is None
                                                and num_beams > 1):
            if num_beams < 2:
                raise ValueError(
                    "beam_search needs num_beams >= 2 (reference generate() "
                    f"semantics), got {num_beams}")
            if prompt_bucket is not None:
                raise ValueError(
                    "prompt_bucket is not supported with beam_search")
            return self.generate_beam(
                input_ids, max_new_tokens=max_new_tokens,
                num_beams=int(num_beams),
                length_penalty=length_penalty, eos_token_id=eos_token_id)
        if num_beams > 1:
            raise ValueError(
                f"num_beams={num_beams} conflicts with "
                f"decode_strategy={decode_strategy!r}; use 'beam_search'")
        if decode_strategy == "greedy_search":
            temperature = 0.0
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call

        cfg = self.config
        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        orig_ids = ids
        b, prompt = ids.shape
        bucketed = prompt_bucket is not None
        if bucketed:
            from ..serving.bucketing import resolve_bucket

            padded_len = resolve_bucket(prompt, prompt_bucket)
            ids = jnp.pad(ids, ((0, 0), (0, padded_len - prompt)))
        else:
            padded_len = prompt
        total = padded_len + max_new_tokens
        if total > cfg.max_seq_len:
            raise ValueError(f"prompt {padded_len}"
                             f"{' (bucketed)' if bucketed else ''} + "
                             f"max_new_tokens {max_new_tokens} exceeds "
                             f"max_seq_len {cfg.max_seq_len}")
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        state = self.state_dict(include_non_persistable_buffer=True)
        params = {k: v._data for k, v in state.items()}
        # KV cache dtype follows the autocast COMPUTE dtype of the attention
        # matmul (the op that reads the cache), not the param dtype: an f32
        # cache under bf16 amp would be converted to bf16 inside the decode
        # loop every step — 2 cache-sized casts per layer per token (~0.7
        # GB/step of pure HBM waste at the bench config; found by
        # tools/decode_hlo_probe.py). Routing through _autocast_dtype_for
        # keeps the white/black-list semantics: a user black-listing the
        # attention op to hold it in f32 keeps the f32 cache.
        from ..core.dispatch import _autocast_dtype_for, amp_ctx as _amp_ctx

        _amp = _amp_ctx()
        _mm_dtype = _autocast_dtype_for("attention", ())
        cache_dtype = (_mm_dtype if _mm_dtype is not None
                       else self.gpt.wte.weight._data.dtype)
        # Matmul-family weights are pre-cast to the autocast compute dtype
        # ONCE, outside the decode loop (weights-in-compute-dtype, the
        # standard inference layout). Relying on per-dispatch casts instead
        # leaves f32 masters in the loop: whether XLA hoists the casts is
        # backend-dependent, and un-hoisted they re-read ~2x the weight
        # bytes every token (the decode loop is weight-bandwidth-bound).
        # 1-D params (biases, norm scales) stay f32: the black-listed norm
        # ops want f32, and per-step casts of [h]-sized biases are noise.
        _w_dtype = _autocast_dtype_for("matmul", ())
        was_training = self.training
        self.eval()

        def sample(logits, key):
            if temperature == 0:
                return jnp.argmax(logits, axis=-1)
            logits = logits / jnp.float32(max(temperature, 1e-6))
            if top_k and top_k > 0:
                # clamp to vocab: top_k >= vocab must mean "keep everything",
                # not an out-of-range [:, -top_k] row index
                k_eff = min(int(top_k), logits.shape[-1])
                kth = jnp.sort(logits, axis=-1)[:, -k_eff][:, None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_l, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # smallest set with cumulative mass >= top_p
                cutoff_idx = jnp.sum(cum < top_p, axis=-1)
                cutoff = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], 1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1)

        from ..core.autograd import no_grad
        from ..jit import _swapped_state, _tracing

        def head(params, h_arr):
            """last-position hidden -> logits, with weights from `params`."""
            with _swapped_state(self, params), _tracing(), no_grad():
                return self._head_logits(Tensor(h_arr))._data

        def run(params, ids, plen, key):
            if _w_dtype is not None:
                params = {k: (v.astype(_w_dtype)
                              if v.ndim >= 2 and jnp.issubdtype(
                                  v.dtype, jnp.floating)
                              else v)
                          for k, v in params.items()}
            # derive the submodule view from the TRACED params argument — a
            # closure over the concrete arrays would bake every weight into
            # the executable as a constant
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}
            caches = [(Tensor(jnp.zeros((b, total, nh, hd), cache_dtype)),
                       Tensor(jnp.zeros((b, total, nh, hd), cache_dtype)),
                       Tensor(jnp.int32(0))) for _ in range(cfg.num_layers)]
            h, caches = functional_call(self.gpt, gpt_params, Tensor(ids),
                                        caches=caches)
            if bucketed:
                # plen is a TRACED scalar: logits come from the last REAL
                # position and decode resumes at offset=plen, so one padded
                # executable serves every prompt length in the bucket. Each
                # generated token overwrites one pad cache row before it is
                # ever attended (causal mask) — numerics match unpadded.
                last_h = jax.lax.dynamic_index_in_dim(h._data, plen - 1, 1,
                                                      keepdims=False)
                caches = [(kc2, vc2, Tensor(plen)) for (kc2, vc2, _o)
                          in caches]
            else:
                last_h = h._data[:, -1]
            logits = head(params, last_h)
            key, sub = jax.random.split(key)
            tok = sample(logits, sub).astype(ids.dtype)
            done = (jnp.zeros((b,), bool) if eos_token_id is None
                    else tok == eos_token_id)
            flat = jax.tree_util.tree_map(lambda t: t._data, caches,
                                          is_leaf=lambda t: isinstance(t, Tensor))

            def step(carry, _):
                flat_caches, tok, key, done = carry
                caches = jax.tree_util.tree_map(Tensor, flat_caches)
                h, caches = functional_call(self.gpt, gpt_params,
                                            Tensor(tok[:, None]),
                                            caches=caches)
                logits = head(params, h._data[:, 0])
                key, sub = jax.random.split(key)
                nxt = sample(logits, sub).astype(tok.dtype)
                if eos_token_id is not None:
                    nxt = jnp.where(done, eos_token_id, nxt)
                    done = done | (nxt == eos_token_id)
                flat_caches = jax.tree_util.tree_map(
                    lambda t: t._data, caches,
                    is_leaf=lambda t: isinstance(t, Tensor))
                return (flat_caches, nxt, key, done), nxt

            if max_new_tokens > 1:
                _, toks = jax.lax.scan(step, (flat, tok, key, done), None,
                                       length=max_new_tokens - 1)
                out = jnp.concatenate([ids, tok[:, None], toks.T], axis=1)
            else:
                out = jnp.concatenate([ids, tok[:, None]], axis=1)
            return out

        try:
            # one compiled decode program per sampling configuration — a fresh
            # jax.jit wrapper each call would recompile every generate().
            # The active amp scope is part of the key: tracing under
            # paddle.amp.auto_cast() bakes bf16 matmuls into the executable
            # (halves decode weight traffic — the decode loop is HBM-bound)
            amp = _amp  # the scope captured above (cache_dtype reads it too)
            # the FULL behavioral tuple: dtype/level AND the op lists that
            # _autocast_dtype_for consults — scopes differing only in
            # white/black lists must not share an executable
            amp_key = ((str(amp.dtype), amp.level, frozenset(amp.white),
                        frozenset(amp.black)) if amp is not None else None)
            # cache_dtype is baked into run()'s closure: key it, or a later
            # call on the no-amp fallback path (param dtype changed, amp_key
            # identical) would retrace the stale closure. Bucketed keys use
            # the RUNG, not the prompt length — the whole bucket shares one
            # executable (plen stays a traced argument).
            cache_key = ("gpt.generate", b, padded_len, bucketed,
                         max_new_tokens, float(temperature), int(top_k),
                         float(top_p), eos_token_id, amp_key,
                         str(cache_dtype))
            fn = _decode_jit_get(self, cache_key, lambda: jax.jit(run))
            out = fn(params, ids, jnp.int32(prompt), jax.random.key(seed))
            if bucketed:
                # reassemble outside the jit: echo the UNPADDED prompt, then
                # the generated tokens (which sit after the padded region) —
                # slicing inside the executable would re-specialize per
                # prompt length and defeat the bucket
                out = jnp.concatenate([orig_ids, out[:, padded_len:]], axis=1)
        finally:
            if was_training:
                self.train()
        return Tensor(out)

    def generate_beam(self, input_ids, max_new_tokens=32, num_beams=4,
                      length_penalty=1.0, eos_token_id=None):
        """Beam-search decode as ONE jitted program (the reference's
        BeamSearchDecoder / beam_search_op machinery, python/paddle's
        generate(decode_strategy="beam_search"), re-designed TPU-native):
        the KV cache carries a beam dim [b*K, total, nh, hd], each scan step
        log-softmaxes all beams' logits, takes top-K over the flattened
        [K*V] continuations, and REORDERS the cache by gathering beam rows —
        static shapes end to end, no host round-trips. Finished beams emit a
        forced eos with log-prob 0 so their score freezes. Returns the best
        beam per batch row, [b, prompt + max_new_tokens], ranked by
        score / length**length_penalty (GNMT-style).
        """
        import jax
        import jax.numpy as jnp

        from ..core.autograd import no_grad
        from ..core.dispatch import _autocast_dtype_for, amp_ctx as _amp_ctx
        from ..core.tensor import Tensor
        from ..jit import _swapped_state, _tracing, functional_call

        cfg = self.config
        K = int(num_beams)
        ids = input_ids._data if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        b, prompt = ids.shape
        total = prompt + max_new_tokens
        if total > cfg.max_seq_len:
            raise ValueError(f"prompt {prompt} + max_new_tokens "
                             f"{max_new_tokens} exceeds max_seq_len "
                             f"{cfg.max_seq_len}")
        nh, hd = cfg.num_heads, cfg.hidden_size // cfg.num_heads
        state = self.state_dict(include_non_persistable_buffer=True)
        params = {k: v._data for k, v in state.items()}
        _amp = _amp_ctx()
        _mm_dtype = _autocast_dtype_for("attention", ())
        cache_dtype = (_mm_dtype if _mm_dtype is not None
                       else self.gpt.wte.weight._data.dtype)
        _w_dtype = _autocast_dtype_for("matmul", ())
        was_training = self.training
        self.eval()
        NEG = jnp.float32(-1e30)

        def head(params, h_arr):
            with _swapped_state(self, params), _tracing(), no_grad():
                return self._head_logits(Tensor(h_arr))._data

        def run(params, ids):
            if _w_dtype is not None:
                params = {k: (v.astype(_w_dtype)
                              if v.ndim >= 2 and jnp.issubdtype(
                                  v.dtype, jnp.floating) else v)
                          for k, v in params.items()}
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}
            # ---- prefill on the raw batch, then tile everything to beams
            caches = [(Tensor(jnp.zeros((b, total, nh, hd), cache_dtype)),
                       Tensor(jnp.zeros((b, total, nh, hd), cache_dtype)),
                       Tensor(jnp.int32(0))) for _ in range(cfg.num_layers)]
            h, caches = functional_call(self.gpt, gpt_params, Tensor(ids),
                                        caches=caches)
            logp0 = jax.nn.log_softmax(
                head(params, h._data[:, -1]).astype(jnp.float32), axis=-1)
            vocab = logp0.shape[-1]
            scores, tok0 = jax.lax.top_k(logp0, K)        # [b, K] each
            toks = jnp.zeros((b, K, max_new_tokens), jnp.int32)
            toks = toks.at[:, :, 0].set(tok0)
            finished = (jnp.zeros((b, K), bool) if eos_token_id is None
                        else tok0 == eos_token_id)
            lengths = jnp.ones((b, K), jnp.float32)  # emitted per beam

            def tile(t):
                a = t._data if isinstance(t, Tensor) else t
                if a.ndim == 0:
                    return a
                return jnp.repeat(a, K, axis=0)  # row i -> beams i*K..i*K+K-1

            flat = [tuple(tile(c) for c in layer) for layer in caches]

            def step(carry, t):
                flat, toks, scores, finished, lengths = carry
                # each beam continues from its last emitted token
                prev = jnp.reshape(
                    jax.lax.dynamic_index_in_dim(
                        jnp.moveaxis(toks, 2, 0), t - 1, 0, keepdims=False),
                    (b * K,))
                caches = [tuple(Tensor(c) for c in layer) for layer in flat]
                h, caches = functional_call(self.gpt, gpt_params,
                                            Tensor(prev[:, None]),
                                            caches=caches)
                logp = jax.nn.log_softmax(
                    head(params, h._data[:, 0]).astype(jnp.float32), axis=-1)
                logp = jnp.reshape(logp, (b, K, vocab))
                if eos_token_id is not None:
                    # finished beams: only "emit eos again, score unchanged"
                    onehot = jnp.where(
                        jnp.arange(vocab)[None, None, :] == eos_token_id,
                        jnp.float32(0), NEG)
                    logp = jnp.where(finished[..., None], onehot, logp)
                cand = scores[..., None] + logp            # [b, K, V]
                flat_cand = jnp.reshape(cand, (b, K * vocab))
                scores, idx = jax.lax.top_k(flat_cand, K)  # [b, K]
                beam_idx = idx // vocab                    # [b, K]
                token = (idx % vocab).astype(jnp.int32)
                # reorder beam state by gathered parent index
                toks = jnp.take_along_axis(toks, beam_idx[..., None], axis=1)
                toks = toks.at[:, :, t].set(token)
                fin_g = jnp.take_along_axis(finished, beam_idx, axis=1)
                len_g = jnp.take_along_axis(lengths, beam_idx, axis=1)
                lengths = jnp.where(fin_g, len_g, len_g + 1.0)
                finished = fin_g if eos_token_id is None else \
                    fin_g | (token == eos_token_id)
                # the functional_call appended this step's K/V for the OLD
                # beam order; gather AFTER the append so each child inherits
                # its parent's cache including the new row
                rows = (jnp.arange(b)[:, None] * K + beam_idx).reshape(-1)
                new_flat = []
                for layer in caches:
                    kc, vc, off = (x._data for x in layer)
                    new_flat.append((kc[rows], vc[rows], off))
                return (new_flat, toks, scores, finished, lengths), None

            if max_new_tokens > 1:
                (flat, toks, scores, finished, lengths), _ = jax.lax.scan(
                    step, (flat, toks, scores, finished, lengths),
                    jnp.arange(1, max_new_tokens))
            # GNMT length penalty; pick the best beam per row
            norm = scores / jnp.power(lengths, jnp.float32(length_penalty))
            best = jnp.argmax(norm, axis=1)                # [b]
            best_toks = jnp.take_along_axis(
                toks, best[:, None, None], axis=1)[:, 0]   # [b, max_new]
            if eos_token_id is not None:
                # positions after the eos repeat eos (matches generate())
                emitted = jnp.cumsum(
                    (best_toks == eos_token_id).astype(jnp.int32), axis=1)
                seen = (emitted - (best_toks == eos_token_id)) > 0
                best_toks = jnp.where(seen, eos_token_id, best_toks)
            return jnp.concatenate([ids, best_toks.astype(ids.dtype)], axis=1)

        try:
            amp = _amp
            amp_key = ((str(amp.dtype), amp.level, frozenset(amp.white),
                        frozenset(amp.black)) if amp is not None else None)
            cache_key = ("gpt.generate_beam", b, prompt, max_new_tokens, K,
                         float(length_penalty), eos_token_id, amp_key,
                         str(cache_dtype))
            fn = _decode_jit_get(self, cache_key, lambda: jax.jit(run))
            out = fn(params, ids)
        finally:
            if was_training:
                self.train()
        return Tensor(out)

"""ERNIE/BERT encoder family — the BASELINE config-3 model (ERNIE-3.0-base
sharding on v5p).

Reference analogue: the ERNIE/BERT configs the fleet sharding tests train
(dygraph_sharding_stage2.py trains a transformer encoder; BASELINE.json names
ERNIE-3.0-base tokens/sec as the sharding north star). Same TPU-first design as
models/gpt.py: TP layers (column→row pairs, vocab-parallel embedding) so every
parameter carries its PartitionSpec dist_attr; dp/sharding come from the engine's
batch + optimizer-state shardings; bidirectional (non-causal) attention.
"""
from __future__ import annotations

import math

from .. import nn
from ..core.tensor import Tensor
from ..distributed.fleet.utils import recompute
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..ops import creation as C
from ..ops import manipulation as P


class ErnieConfig:
    def __init__(self, vocab_size=40000, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_hidden_size=None, max_seq_len=512,
                 type_vocab_size=4, dropout=0.1, attention_dropout=0.1,
                 use_recompute=False, tie_word_embeddings=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.attention_dropout = attention_dropout
        self.use_recompute = use_recompute
        self.tie_word_embeddings = tie_word_embeddings


def ernie_tiny(**kw):
    kw.setdefault("dropout", 0.0)
    kw.setdefault("attention_dropout", 0.0)
    return ErnieConfig(vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
                       max_seq_len=128, **kw)


def ernie_base(**kw):
    """ERNIE-3.0-base shape (BASELINE config 3)."""
    return ErnieConfig(vocab_size=40000, hidden_size=768, num_layers=12,
                       num_heads=12, max_seq_len=512, **kw)


def ernie_large(**kw):
    return ErnieConfig(vocab_size=40000, hidden_size=1024, num_layers=24,
                       num_heads=16, max_seq_len=512, **kw)


class ErnieSelfAttention(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        self.hidden_size = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(config.hidden_size,
                                             3 * config.hidden_size,
                                             gather_output=False)
        self.out_proj = RowParallelLinear(config.hidden_size, config.hidden_size,
                                          input_is_parallel=True)
        self.attn_dropout = config.attention_dropout

    def forward(self, x, attn_mask=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = P.reshape(qkv, (b, s, 3, self.num_heads, self.head_dim))
        q, k, v = P.unbind(qkv, axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.attn_dropout, training=self.training)
        out = P.reshape(out, (b, s, self.hidden_size))
        return self.out_proj(out)


class ErnieBlock(nn.Layer):
    """Post-LN encoder block (BERT/ERNIE convention, unlike GPT's pre-LN)."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.attn = ErnieSelfAttention(config)
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.fc1 = ColumnParallelLinear(config.hidden_size, config.ffn_hidden_size,
                                        gather_output=False)
        self.fc2 = RowParallelLinear(config.ffn_hidden_size, config.hidden_size,
                                     input_is_parallel=True)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.dropout = config.dropout
        self.use_recompute = config.use_recompute

    def _forward(self, x, attn_mask=None):
        h = self.ln1(x + F.dropout(self.attn(x, attn_mask), self.dropout,
                                   training=self.training))
        ffn = self.fc2(F.gelu(self.fc1(h), approximate=True))
        return self.ln2(h + F.dropout(ffn, self.dropout, training=self.training))

    def forward(self, x, attn_mask=None):
        if self.use_recompute and self.training:
            return recompute(self._forward, x, attn_mask)
        return self._forward(x, attn_mask)


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.word_emb = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.pos_emb = nn.Embedding(config.max_seq_len, config.hidden_size)
        self.type_emb = nn.Embedding(config.type_vocab_size, config.hidden_size)
        self.emb_ln = nn.LayerNorm(config.hidden_size)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([ErnieBlock(config)
                                    for _ in range(config.num_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        s = input_ids.shape[1]
        pos = C.arange(0, s, dtype="int64")
        x = self.word_emb(input_ids) + self.pos_emb(pos)
        if token_type_ids is not None:
            x = x + self.type_emb(token_type_ids)
        x = self.drop(self.emb_ln(x))
        mask = None
        if attention_mask is not None:
            # [b, s] 1/0 -> additive [b, 1, 1, s]
            mask = (1.0 - attention_mask.astype("float32")) * -1e4
            mask = P.reshape(mask, (mask.shape[0], 1, 1, mask.shape[1]))
        for blk in self.blocks:
            x = blk(x, mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForPretraining(nn.Layer):
    """MLM + sentence-order head over the encoder (the reference pretraining
    objective shape); returns the combined loss."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.mlm_transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.mlm_ln = nn.LayerNorm(config.hidden_size)
        if not config.tie_word_embeddings:
            self.mlm_decoder = ColumnParallelLinear(config.hidden_size,
                                                    config.vocab_size)
        self.nsp_head = nn.Linear(config.hidden_size, 2)
        self.loss_fn = ParallelCrossEntropy(ignore_index=-100)
        self.config = config

    def logits(self, hidden):
        h = self.mlm_ln(F.gelu(self.mlm_transform(hidden), approximate=True))
        if self.config.tie_word_embeddings:
            return P.reshape(
                h, (-1, h.shape[-1])) @ self.ernie.word_emb.weight.t()
        return self.mlm_decoder(P.reshape(h, (-1, h.shape[-1])))

    def forward(self, input_ids, labels, token_type_ids=None, attention_mask=None,
                next_sentence_label=None):
        hidden, pooled = self.ernie(input_ids, token_type_ids, attention_mask)
        logits = self.logits(hidden)
        mlm_loss = self.loss_fn(logits, P.reshape(labels, (-1, 1))).mean()
        if next_sentence_label is not None:
            nsp_logits = self.nsp_head(pooled)
            nsp_loss = F.softmax_with_cross_entropy(
                nsp_logits, next_sentence_label).mean()
            return mlm_loss + nsp_loss
        return mlm_loss


# BERT aliases: same architecture, WordPiece-era defaults
BertConfig = ErnieConfig
BertModel = ErnieModel
BertForPretraining = ErnieForPretraining


def bert_base(**kw):
    return ErnieConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                       num_heads=12, max_seq_len=512, type_vocab_size=2, **kw)


def bert_large(**kw):
    return ErnieConfig(vocab_size=30522, hidden_size=1024, num_layers=24,
                       num_heads=16, max_seq_len=512, type_vocab_size=2, **kw)
